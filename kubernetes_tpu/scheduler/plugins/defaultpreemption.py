"""DefaultPreemption: PostFilter that evicts lower-priority victims.

Parity target: pkg/scheduler/framework/preemption/preemption.go
(`Evaluator.Preempt`: find candidates → pick min-cost node → delete victims →
set status.nominatedNodeName) + plugins/defaultpreemption/default_preemption.go
(`SelectVictimsOnNode`: dry-run removing lower-priority pods, re-run Filter,
add back as many as possible in priority order; `pickOneNodeForPreemption`
ordering: fewest PDB violations → lowest max victim priority → smallest
priority sum → fewest victims → latest start time).

TPU-first (SURVEY §7 phase 6 "preemption as solve-with-victim-relaxation"):
the candidate search is VECTORIZED over a wave. A preemption wave (a batch
of failed high-priority pods) shares one dense tensor state — per node, the
priority-ascending victim prefix: cumulative releasable resources, priority
prefix sums/maxima. Per preemptor, the minimal victim count per node and
the reference's cost ordering are numpy reductions over (N, Kmax); only
the CHOSEN candidate is re-verified with the full host Filter chain (one
dry-run, not N), falling back to the next-best candidate on mismatch.
Victims claimed by earlier preemptors in the wave are excluded and the
preemptor's own consumption is charged, so concurrent preemptors spread
instead of stacking on one node. The reprieve subtlety (a non-resource
filter re-admitting a mid-priority resident) is covered by the exact
verify: on divergence the per-node host scan (`_select_victims`) answers.
"""

from __future__ import annotations

import random

from typing import Mapping

import numpy as np

from kubernetes_tpu.scheduler.framework import (
    CycleState,
    Plugin,
    Status,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
)
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot


class _WaveState:
    """Dense victim-relaxation tensors for one snapshot generation.

    Arrays (N nodes × Kmax victim prefix × R resources):
    - rel[n, k, r]: resources released by evicting the k+1 lowest-priority
      unclaimed residents of node n
    - vprio[n, k]: priority of the k-th victim (asc); INT_MAX padding
    - vsum/vmax[n, k]: priority prefix sums / maxima
    - used[n, r] / alloc[n, r], pods_used/alloc[n]
    """

    __slots__ = ("nodes", "resources", "r_index", "rel", "vreq", "vprio",
                 "vsum", "vmax", "vcount", "used", "alloc", "pods_used",
                 "pods_alloc", "victims", "generation", "names_hash")

    INF = np.iinfo(np.int64).max

    def __init__(self, snapshot: Snapshot, claimed: set[str],
                 promised: dict[str, list[dict]]):
        nodes = list(snapshot.nodes)
        self.nodes = nodes
        self.generation = getattr(snapshot, "generation", None)
        #: node-order fingerprint: DiagMap.banned_mask rows from the
        #: solve-time snapshot only apply when the order still matches.
        self.names_hash = hash(tuple(ni.name for ni in nodes))
        res: dict[str, None] = {}
        for ni in nodes:
            for r in ni.allocatable.res:
                res.setdefault(r)
        self.resources = list(res)
        self.r_index = {r: j for j, r in enumerate(self.resources)}
        N, R = len(nodes), len(self.resources)
        kmax = 1
        per_node: list[list[PodInfo]] = []
        for ni in nodes:
            cand = sorted(
                (p for p in ni.pods if p.key not in claimed),
                key=lambda p: (p.priority, p.key))
            per_node.append(cand)
            kmax = max(kmax, len(cand))
        self.victims = per_node
        self.rel = np.zeros((N, kmax, R), dtype=np.int64)
        #: per-victim request vectors (rel is their prefix sum) — the
        #: device proposal scan re-derives prefixes after in-scan claims,
        #: which needs the per-victim granularity.
        self.vreq = np.zeros((N, kmax, R), dtype=np.int64)
        self.vprio = np.full((N, kmax), self.INF, dtype=np.int64)
        self.vsum = np.zeros((N, kmax), dtype=np.int64)
        self.vmax = np.zeros((N, kmax), dtype=np.int64)
        self.vcount = np.zeros((N,), dtype=np.int64)
        self.used = np.zeros((N, R), dtype=np.int64)
        self.alloc = np.zeros((N, R), dtype=np.int64)
        self.pods_used = np.zeros((N,), dtype=np.int64)
        self.pods_alloc = np.zeros((N,), dtype=np.int64)
        for n, ni in enumerate(nodes):
            for r, v in ni.requested.res.items():
                j = self.r_index.get(r)
                if j is not None:
                    self.used[n, j] = v
            for r, v in ni.allocatable.res.items():
                self.alloc[n, self.r_index[r]] = v
            self.pods_used[n] = ni.requested.pods
            self.pods_alloc[n] = ni.allocatable.pods
            # Unbound-but-promised preemptors charge their target node.
            for q, _ts in (promised.get(ni.name) or {}).values():
                for r, v in q.items():
                    j = self.r_index.get(r)
                    if j is not None:
                        self.used[n, j] += v
                self.pods_used[n] += 1
            cand = per_node[n]
            self.vcount[n] = len(cand)
            acc = np.zeros((R,), dtype=np.int64)
            psum = 0
            pmax = 0
            for k, p in enumerate(cand):
                for r, v in p.requests.items():
                    j = self.r_index.get(r)
                    if j is not None:
                        acc[j] += v
                        self.vreq[n, k, j] = v
                psum += p.priority
                pmax = max(pmax, p.priority)
                self.rel[n, k] = acc
                self.vprio[n, k] = p.priority
                self.vsum[n, k] = psum
                self.vmax[n, k] = pmax

    def candidates(self, pod: PodInfo,
                   banned: set[int]) -> list[tuple[int, int]]:
        """[(node index, victim count)] sorted by the reference cost
        ordering — each entry is the MINIMAL victim prefix on that node
        that fits the pod (resources + pod count), victims restricted to
        priorities below the preemptor's."""
        N, kmax, R = self.rel.shape
        q = np.zeros((R,), dtype=np.int64)
        for r, v in pod.requests.items():
            j = self.r_index.get(r)
            if j is not None:
                q[j] = v
        # eligible[n, k]: prefix k+1 consists solely of lower-prio victims
        eligible = self.vprio < pod.priority
        fits = np.all(
            self.used[:, None, :] - self.rel + q[None, None, :]
            <= self.alloc[:, None, :], axis=-1)
        fits &= (self.pods_used[:, None] - (np.arange(kmax)[None, :] + 1)
                 + 1 <= self.pods_alloc[:, None])
        ok = eligible & fits
        any_ok = ok.any(axis=1)
        if banned:
            for n in banned:
                any_ok[n] = False
        idxs = np.nonzero(any_ok)[0]
        if idxs.size == 0:
            return []
        kmin = ok[idxs].argmax(axis=1)  # first fitting prefix per node
        vmax = self.vmax[idxs, kmin]
        vsum = self.vsum[idxs, kmin]
        order = np.lexsort((idxs, kmin + 1, vsum, vmax))
        return [(int(idxs[i]), int(kmin[i]) + 1) for i in order]

    def claim(self, n: int, count: int, pod: PodInfo,
              claimed: set[str], promised: dict) -> list[PodInfo]:
        """Commit a choice: mark victims claimed, charge the preemptor,
        and refresh node n's tensors IN PLACE (O(K·R)) — a full rebuild
        per preemptor made 1000-node waves O(wave² ) in python loops."""
        import time
        victims = self.victims[n][:count]
        for v in victims:
            claimed.add(v.key)
        promised.setdefault(self.nodes[n].name, {})[pod.key] = (
            dict(pod.requests), time.monotonic())
        # Victims leave, the preemptor's load lands.
        remaining = self.victims[n][count:]
        self.victims[n] = remaining
        for v in victims:
            for r, val in v.requests.items():
                j = self.r_index.get(r)
                if j is not None:
                    self.used[n, j] -= val
        for r, val in pod.requests.items():
            j = self.r_index.get(r)
            if j is not None:
                self.used[n, j] += val
        self.pods_used[n] += 1 - count
        self.rel[n] = 0
        self.vreq[n] = 0
        self.vprio[n] = self.INF
        self.vsum[n] = 0
        self.vmax[n] = 0
        self.vcount[n] = len(remaining)
        acc = np.zeros((self.rel.shape[2],), dtype=np.int64)
        psum = 0
        pmax = 0
        for k, p in enumerate(remaining):
            for r, val in p.requests.items():
                j = self.r_index.get(r)
                if j is not None:
                    acc[j] += val
                    self.vreq[n, k, j] = val
            psum += p.priority
            pmax = max(pmax, p.priority)
            self.rel[n, k] = acc
            self.vprio[n, k] = p.priority
            self.vsum[n, k] = psum
            self.vmax[n, k] = pmax
        return list(victims)


class DefaultPreemption(Plugin):
    NAME = "DefaultPreemption"
    EXTENSION_POINTS = ("PostFilter",)

    def __init__(self, args=None, framework=None, evict=None):
        """`framework` runs the Filter dry-runs; `evict(pod_key, victim_keys,
        node)` is the side-effect callback the scheduler injects (API deletes
        + nominatedNodeName patch happen there)."""
        super().__init__(args)
        self.framework = framework
        self.evict = evict
        self._rng = random.Random(self.args.get("seed", 0))
        #: wave tensors: kept across a preemption wave with in-place claim
        #: updates; resynced to the live snapshot on a budget (claims are
        #: exact in-wave, external drift is caught by the live verify).
        self._wave: _WaveState | None = None
        self._wave_claims = 0
        self._wave_built = 0.0
        #: victim keys promised to earlier preemptors; pruned when the
        #: victim is no longer resident (its deletion landed).
        self._claimed: set[str] = set()
        #: node name -> {preemptor pod key -> (requests, promised-at)};
        #: entries drop when the pod binds (appears among residents), when
        #: it re-nominates elsewhere, or on TTL (pod deleted pre-bind).
        self._promised: dict[str, dict[str, tuple]] = {}
        self._promised_pods: dict[str, str] = {}  # pod key -> node name
        #: pod key -> victim keys evicted for it — while any is still
        #: resident on the promised node, a retry re-nominates the same
        #: node WITHOUT a second eviction (preemption.go
        #: PodEligibleToPreemptOthers: a preemptor whose victims are still
        #: terminating must not preempt again).
        self._promised_victims: dict[str, list[str]] = {}
        #: device-proposed (wave, node, count) per pod key — see prime_wave.
        self._primed: dict[str, tuple] = {}

    def _in_flight_node(self, pod: PodInfo, snapshot: Snapshot) -> str | None:
        """The node already promised to this pod, if its eviction is still
        in flight (some claimed victim remains resident there). Retries
        re-nominate it instead of evicting a second set of victims."""
        node = self._promised_pods.get(pod.key)
        if node is None:
            return None
        vkeys = self._promised_victims.get(pod.key)
        if not vkeys:
            return None
        ni = snapshot.get(node)
        if ni is None:
            return None
        resident = {p.key for p in ni.pods}
        return node if any(vk in resident for vk in vkeys) else None

    def prime_wave(self, pods: list[PodInfo], snapshot: Snapshot,
                   statuses_by_pod: Mapping[str, Mapping[str, Status]]
                   ) -> None:
        """Batched device victim proposal for a failure wave (SURVEY §7
        phase 6): ONE `solver.propose_victims` call ranks a candidate per
        (preemptor, node) for every resolvable failed pod, threading
        in-wave claims on device. `post_filter` then verifies each primed
        proposal against the live snapshot with the full Filter chain and
        evicts exactly as before — only the SEARCH moved off host.

        Proposals assume claims land in wave order; a host-verify
        divergence (stale wave, non-resource filter) drops to the ranked
        host search for that pod, and every later proposal is still
        individually verified before use."""
        self._primed.clear()
        if self.framework is None or not pods:
            return
        wave = self._wave_state(snapshot)
        name_to_idx = {ni.name: n for n, ni in enumerate(wave.nodes)}
        elig: list[PodInfo] = []
        banned_rows: list[np.ndarray] = []
        N = len(wave.nodes)
        for pi in pods:
            if self._in_flight_node(pi, snapshot) is not None:
                continue  # the guard answers without a new eviction
            statuses = statuses_by_pod.get(pi.key) or {}
            # DiagMap (the batched backend's diagnostics) precomputes both
            # aggregates; plain dicts take the O(N) scan.
            bm = getattr(statuses, "banned_mask", None)
            if bm is not None and \
                    statuses.banned_nodes_hash == wave.names_hash:
                if not statuses.resolvable:
                    continue
                ban = bm
            else:
                ban = np.zeros((N,), dtype=bool)
                resolvable = not statuses
                for name, st in statuses.items():
                    if st.code == UNSCHEDULABLE_AND_UNRESOLVABLE:
                        j = name_to_idx.get(name)
                        if j is not None:
                            ban[j] = True
                    else:
                        resolvable = True
                if not resolvable:
                    continue  # _handle_failure won't run PostFilter on it
            elig.append(pi)
            banned_rows.append(ban)
        if not elig:
            return
        from kubernetes_tpu.ops import solver
        import jax.numpy as jnp
        R = wave.rel.shape[2]
        # FIXED preemptor bucket + power-of-two victim-prefix padding:
        # wave widths vary per batch, and an exact-shape jit signature
        # would recompile the scan per distinct width. Waves wider than
        # the bucket run in chunks, threading the post-claim device carry
        # (the scan state IS the claim ledger). Padding rows carry
        # INT32_MIN priority + all-banned, so they propose nothing.
        P = len(elig)
        PB = self.WAVE_DEVICE_BUCKET
        K = wave.vreq.shape[1]
        K2 = max(8, 1 << (K - 1).bit_length())
        cap = 2**31 - 1
        req64 = np.zeros((P, R), dtype=np.int64)
        prio = np.zeros((P,), dtype=np.int32)
        for i, pi in enumerate(elig):
            for r, v in pi.requests.items():
                j = wave.r_index.get(r)
                if j is not None:
                    req64[i, j] = v
            prio[i] = min(pi.priority, cap - 1)
        banned = np.stack(banned_rows)
        # Conservative per-column power-of-two quantization: byte
        # quantities (memory, ephemeral-storage) overflow int32, and the
        # scan cumsums released resources — so scale each column until
        # its max fits 2^30 (headroom for the in-scan sums). Rounding
        # direction is one-sided: consumption (used, preemptor request)
        # rounds UP, supply (alloc, released victim resources) rounds
        # DOWN, so a scaled "fits" always implies a true fit; the rare
        # false reject only costs a fallback to the ranked host search.
        lim = np.int64(1 << 30)
        colmax = np.maximum(wave.alloc.max(axis=0, initial=0),
                            wave.used.max(axis=0, initial=0))
        colmax = np.maximum(colmax, req64.max(axis=0, initial=0))
        shift = np.zeros((R,), dtype=np.int64)
        over = colmax > lim
        if over.any():
            shift[over] = np.ceil(
                np.log2(colmax[over] / lim)).astype(np.int64)

        def up(a):  # consumption: ceil
            return ((a + (np.int64(1) << shift) - 1) >> shift).astype(
                np.int32)

        def down(a):  # supply: floor
            return (a >> shift).astype(np.int32)

        req = up(req64)
        vreq = np.zeros((N, K2, R), dtype=np.int32)
        vreq[:, :K] = down(wave.vreq)
        vprio = np.full((N, K2), cap, dtype=np.int32)
        vprio[:, :K] = np.minimum(wave.vprio, cap)
        carry = (jnp.asarray(up(wave.used)),
                 jnp.asarray(down(wave.alloc)),
                 jnp.asarray(wave.pods_used.astype(np.int32)),
                 jnp.asarray(wave.pods_alloc.astype(np.int32)),
                 jnp.asarray(vreq), jnp.asarray(vprio))
        used_d, alloc_d, pused_d, palloc_d, vreq_d, vprio_d = carry
        nodes_out = np.empty((P,), dtype=np.int32)
        counts_out = np.empty((P,), dtype=np.int32)
        for lo in range(0, P, PB):
            hi = min(lo + PB, P)
            w = hi - lo
            req_c = np.zeros((PB, R), dtype=np.int32)
            req_c[:w] = req[lo:hi]
            prio_c = np.full((PB,), -2**31, dtype=np.int32)
            prio_c[:w] = prio[lo:hi]
            ban_c = np.ones((PB, N), dtype=bool)
            ban_c[:w] = banned[lo:hi]
            offsets = np.fromiter(
                (self._rng.randrange(N) for _ in range(PB)),
                dtype=np.int32, count=PB)
            node, count, used_d, pused_d, vreq_d, vprio_d = \
                solver.propose_victims(
                    jnp.asarray(req_c), jnp.asarray(prio_c),
                    jnp.asarray(ban_c), used_d, alloc_d, pused_d,
                    palloc_d, vreq_d, vprio_d, jnp.asarray(offsets))
            nodes_out[lo:hi] = np.asarray(node)[:w]
            counts_out[lo:hi] = np.asarray(count)[:w]
        for i, pi in enumerate(elig):
            if nodes_out[i] >= 0:
                self._primed[pi.key] = (N, int(nodes_out[i]),
                                        int(counts_out[i]))

    def post_filter(self, state: CycleState, pod: PodInfo, snapshot: Snapshot,
                    filtered_status: Mapping[str, Status]) -> tuple[str, Status]:
        if self.framework is None:
            return "", Status.unschedulable()
        in_flight = self._in_flight_node(pod, snapshot)
        if in_flight is not None:
            return in_flight, Status.success()
        wave = self._wave_state(snapshot)
        # Device-primed proposal (prime_wave): verify + commit without the
        # ranked host search. Validation is SEMANTIC, not wave-identity —
        # the wave resync budget (WAVE_MAX_CLAIMS/AGE) rebuilds mid-wave,
        # and a rebuilt wave's minimal prefix on the proposed node is still
        # a valid (claimed-victim-free) choice; the full live-filter verify
        # in _verify_and_commit guards feasibility either way. Primes that
        # no longer have an eligible prefix fall to the ranked path below.
        primed = self._primed.pop(pod.key, None)
        if primed is not None and primed[0] == len(wave.nodes):
            n, count = primed[1], primed[2]
            if count <= len(wave.victims[n]) and all(
                    v.priority < pod.priority
                    for v in wave.victims[n][:count]):
                committed = self._verify_and_commit(
                    state, pod, snapshot, wave, n, count)
                if committed is not None:
                    return committed, Status.success()
        banned: set[int] = set()
        # Nodes rejected as UnschedulableAndUnresolvable can't be helped by
        # preemption (preemption.go `nodesWherePreemptionMightHelp`).
        bm = getattr(filtered_status, "banned_mask", None)
        if bm is not None and \
                filtered_status.banned_nodes_hash == wave.names_hash:
            banned = set(np.nonzero(bm)[0])
        else:
            for n, ni in enumerate(wave.nodes):
                st = filtered_status.get(ni.name)
                if st is not None and \
                        st.code == UNSCHEDULABLE_AND_UNRESOLVABLE:
                    banned.add(n)
        ranked = wave.candidates(pod, banned)
        # Seeded tie shuffle among equal-cost leaders (the reference scans
        # a Go map whose iteration order is randomized, which spreads
        # concurrent preemptors across equal-cost nodes — a deterministic
        # first-min made every preemptor in a wave nominate the SAME node
        # and retry quadratically).
        if len(ranked) > 1:
            lead_cost = self._cost_of(wave, ranked[0])
            tie_end = 1
            while tie_end < len(ranked) and \
                    self._cost_of(wave, ranked[tie_end]) == lead_cost:
                tie_end += 1
            head = ranked[:tie_end]
            self._rng.shuffle(head)
            ranked = head + ranked[tie_end:]
        # Exact verify on the chosen candidate only; on divergence (a
        # non-resource filter still failing), try the next best, then the
        # per-node host scan.
        for attempt, (n, count) in enumerate(ranked):
            if attempt >= 8:
                break
            committed = self._verify_and_commit(
                state, pod, snapshot, wave, n, count)
            if committed is not None:
                return committed, Status.success()
        return self._post_filter_scan(state, pod, snapshot, filtered_status)

    def _verify_and_commit(self, state: CycleState, pod: PodInfo,
                           snapshot: Snapshot, wave: _WaveState,
                           n: int, count: int) -> str | None:
        """Verify one (node, victim count) candidate against the LIVE node
        with the full Filter chain (the wave may be a bounded-age batch
        view); on success, claim in the wave ledger and evict. Returns the
        node name, or None on divergence."""
        ni = wave.nodes[n]
        victims = wave.victims[n][:count]
        live_ni = snapshot.get(ni.name) or ni
        dry = live_ni.clone()
        for v in victims:
            dry.remove_pod(v.key)
        if not self.framework.run_filters(
                state.clone(), pod, dry).is_success():
            return None
        self._drop_promise(pod.key)  # re-nomination moves the charge
        chosen = wave.claim(n, count, pod, self._claimed, self._promised)
        self._promised_pods[pod.key] = ni.name
        self._promised_victims[pod.key] = [v.key for v in chosen]
        self._wave_claims += 1
        if self.evict is not None:
            self.evict(pod, [v.key for v in chosen], ni.name)
        return ni.name

    @staticmethod
    def _cost_of(wave: _WaveState, entry: tuple[int, int]):
        n, count = entry
        return (int(wave.vmax[n, count - 1]), int(wave.vsum[n, count - 1]),
                count)

    #: fixed preemptor-axis width of one propose_victims call: one jit
    #: signature regardless of wave width (wider waves chunk + thread the
    #: device carry; narrower ones pad with inert rows).
    WAVE_DEVICE_BUCKET = 128
    #: resync budget: rebuild from the live snapshot after this many
    #: claims or this much wall time, whichever first. Claims are exact
    #: in-wave (in-place ledger) and every candidate is live-verified
    #: before eviction, so the budget only bounds cost-ranking staleness;
    #: 512 lets a 1k-preemptor wave run with ~2 rebuilds instead of 8.
    WAVE_MAX_CLAIMS = 512
    WAVE_MAX_AGE_S = 0.5
    #: a nominated preemptor that never binds stops being charged.
    PROMISE_TTL_S = 30.0

    def _drop_promise(self, pod_key: str) -> None:
        self._promised_victims.pop(pod_key, None)
        node = self._promised_pods.pop(pod_key, None)
        if node is not None:
            entries = self._promised.get(node)
            if entries is not None:
                entries.pop(pod_key, None)
                if not entries:
                    self._promised.pop(node, None)

    def _wave_state(self, snapshot: Snapshot) -> _WaveState:
        import time
        wave = self._wave
        if wave is not None and len(wave.nodes) == len(snapshot.nodes) \
                and self._wave_claims < self.WAVE_MAX_CLAIMS \
                and time.monotonic() - self._wave_built < self.WAVE_MAX_AGE_S:
            return wave
        # Prune ledgers against live residency before rebuilding: a
        # claimed victim still resident keeps its claim (delete in
        # flight); one that vanished is done. A promised preemptor that
        # bound is now a resident and stops being charged separately;
        # one that never binds (deleted pre-bind) ages out on TTL.
        resident: set[str] = set()
        for ni in snapshot.nodes:
            for p in ni.pods:
                resident.add(p.key)
        self._claimed &= resident
        now = time.monotonic()
        for node in list(self._promised):
            entries = self._promised[node]
            for pk in list(entries):
                _reqs, ts = entries[pk]
                if pk in resident or now - ts > self.PROMISE_TTL_S:
                    entries.pop(pk, None)
                    self._promised_pods.pop(pk, None)
                    self._promised_victims.pop(pk, None)
            if not entries:
                self._promised.pop(node, None)
        wave = _WaveState(snapshot, self._claimed, self._promised)
        self._wave = wave
        self._wave_claims = 0
        self._wave_built = time.monotonic()
        return wave

    # -- legacy exact scan (fallback + differential reference) -------------

    def _post_filter_scan(self, state: CycleState, pod: PodInfo,
                          snapshot: Snapshot,
                          filtered_status: Mapping[str, Status]
                          ) -> tuple[str, Status]:
        candidates: list[tuple[str, list[PodInfo]]] = []
        for node in snapshot:
            st = filtered_status.get(node.name)
            if st is not None and st.code == UNSCHEDULABLE_AND_UNRESOLVABLE:
                continue
            victims = self._select_victims(state, pod, node)
            if victims is not None:
                candidates.append((node.name, victims))
        if not candidates:
            return "", Status.unschedulable(
                "preemption: 0/%d nodes are available" % len(snapshot))
        import time
        node_name, victims = self._pick_one(candidates)
        for v in victims:
            self._claimed.add(v.key)
        self._drop_promise(pod.key)
        self._promised.setdefault(node_name, {})[pod.key] = (
            dict(pod.requests), time.monotonic())
        self._promised_pods[pod.key] = node_name
        self._promised_victims[pod.key] = [v.key for v in victims]
        self._wave = None
        if self.evict is not None:
            self.evict(pod, [v.key for v in victims], node_name)
        return node_name, Status.success()

    def _select_victims(self, state: CycleState, pod: PodInfo,
                        node: NodeInfo) -> list[PodInfo] | None:
        """Dry-run: remove ALL lower-priority pods; if pod fits, add back as
        many as possible (highest priority first), keeping feasibility."""
        lower = [p for p in node.pods
                 if p.priority < pod.priority and p.key not in self._claimed]
        if not lower:
            return None
        dry = node.clone()
        for v in lower:
            dry.remove_pod(v.key)
        if not self.framework.run_filters(state.clone(), pod, dry).is_success():
            return None
        # Reprieve pass: re-add in priority-desc order while still feasible.
        victims: list[PodInfo] = []
        for v in sorted(lower, key=lambda p: -p.priority):
            dry.add_pod(v)
            if self.framework.run_filters(state.clone(), pod, dry).is_success():
                continue  # reprieved
            dry.remove_pod(v.key)
            victims.append(v)
        return victims if victims else None

    def _pick_one(self, candidates: list[tuple[str, list[PodInfo]]]
                  ) -> tuple[str, list[PodInfo]]:
        """pickOneNodeForPreemption cost ordering (no PDB tier yet —
        disruption controller integration adds it)."""
        def cost(entry):
            _, victims = entry
            return (
                max((v.priority for v in victims), default=0),
                sum(v.priority for v in victims),
                len(victims),
            )
        best = min(cost(e) for e in candidates)
        ties = [e for e in candidates if cost(e) == best]
        return ties[self._rng.randrange(len(ties))]
