"""DefaultPreemption: PostFilter that evicts lower-priority victims.

Parity target: pkg/scheduler/framework/preemption/preemption.go
(`Evaluator.Preempt`: find candidates → pick min-cost node → delete victims →
set status.nominatedNodeName) + plugins/defaultpreemption/default_preemption.go
(`SelectVictimsOnNode`: dry-run removing lower-priority pods, re-run Filter,
add back as many as possible in priority order; `pickOneNodeForPreemption`
ordering: fewest PDB violations → lowest max victim priority → smallest
priority sum → fewest victims → latest start time).

The dry-run uses cloned NodeInfo so the live snapshot is untouched.
"""

from __future__ import annotations

import random

from typing import Mapping

from kubernetes_tpu.scheduler.framework import (
    CycleState,
    Plugin,
    Status,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
)
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot


class DefaultPreemption(Plugin):
    NAME = "DefaultPreemption"
    EXTENSION_POINTS = ("PostFilter",)

    def __init__(self, args=None, framework=None, evict=None):
        """`framework` runs the Filter dry-runs; `evict(pod_key, victim_keys,
        node)` is the side-effect callback the scheduler injects (API deletes
        + nominatedNodeName patch happen there)."""
        super().__init__(args)
        self.framework = framework
        self.evict = evict
        self._rng = random.Random(self.args.get("seed", 0))

    def post_filter(self, state: CycleState, pod: PodInfo, snapshot: Snapshot,
                    filtered_status: Mapping[str, Status]) -> tuple[str, Status]:
        if self.framework is None:
            return "", Status.unschedulable()
        # Nodes rejected as UnschedulableAndUnresolvable can't be helped by
        # preemption (preemption.go `nodesWherePreemptionMightHelp`).
        candidates: list[tuple[str, list[PodInfo]]] = []
        for node in snapshot:
            st = filtered_status.get(node.name)
            if st is not None and st.code == UNSCHEDULABLE_AND_UNRESOLVABLE:
                continue
            victims = self._select_victims(state, pod, node)
            if victims is not None:
                candidates.append((node.name, victims))
        if not candidates:
            return "", Status.unschedulable(
                "preemption: 0/%d nodes are available" % len(snapshot))
        node_name, victims = self._pick_one(candidates)
        if self.evict is not None:
            self.evict(pod, [v.key for v in victims], node_name)
        return node_name, Status.success()

    def _select_victims(self, state: CycleState, pod: PodInfo,
                        node: NodeInfo) -> list[PodInfo] | None:
        """Dry-run: remove ALL lower-priority pods; if pod fits, add back as
        many as possible (highest priority first), keeping feasibility."""
        lower = [p for p in node.pods if p.priority < pod.priority]
        if not lower:
            return None
        dry = node.clone()
        for v in lower:
            dry.remove_pod(v.key)
        if not self.framework.run_filters(state.clone(), pod, dry).is_success():
            return None
        # Reprieve pass: re-add in priority-desc order while still feasible.
        victims: list[PodInfo] = []
        for v in sorted(lower, key=lambda p: -p.priority):
            dry.add_pod(v)
            if self.framework.run_filters(state.clone(), pod, dry).is_success():
                continue  # reprieved
            dry.remove_pod(v.key)
            victims.append(v)
        return victims if victims else None

    def _pick_one(self, candidates: list[tuple[str, list[PodInfo]]]
                  ) -> tuple[str, list[PodInfo]]:
        """pickOneNodeForPreemption cost ordering (no PDB tier yet —
        disruption controller integration adds it). Ties break RANDOMLY
        (seeded): the reference scans a Go map whose iteration order is
        randomized, which spreads concurrent preemptors across equal-cost
        nodes — a deterministic first-min made every preemptor in a wave
        nominate the SAME node and retry quadratically."""
        def cost(entry):
            _, victims = entry
            return (
                max((v.priority for v in victims), default=0),
                sum(v.priority for v in victims),
                len(victims),
            )
        best = min(cost(e) for e in candidates)
        ties = [e for e in candidates if cost(e) == best]
        return ties[self._rng.randrange(len(ties))]
