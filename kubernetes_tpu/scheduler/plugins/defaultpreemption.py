"""DefaultPreemption: PostFilter that evicts lower-priority victims.

Parity target: pkg/scheduler/framework/preemption/preemption.go
(`Evaluator.Preempt`: find candidates → pick min-cost node → delete victims →
set status.nominatedNodeName) + plugins/defaultpreemption/default_preemption.go
(`SelectVictimsOnNode`: dry-run removing lower-priority pods, re-run Filter,
add back as many as possible in priority order; `pickOneNodeForPreemption`
ordering: fewest PDB violations → lowest max victim priority → smallest
priority sum → fewest victims → latest start time).

TPU-first (SURVEY §7 phase 6 "preemption as solve-with-victim-relaxation"):
the candidate search is VECTORIZED over a wave. A preemption wave (a batch
of failed high-priority pods) shares one dense tensor state — per node, the
priority-ascending victim prefix: cumulative releasable resources, priority
prefix sums/maxima. Per preemptor, the minimal victim count per node and
the reference's cost ordering are numpy reductions over (N, Kmax); only
the CHOSEN candidate is re-verified with the full host Filter chain (one
dry-run, not N), falling back to the next-best candidate on mismatch.
Victims claimed by earlier preemptors in the wave are excluded and the
preemptor's own consumption is charged, so concurrent preemptors spread
instead of stacking on one node. The reprieve subtlety (a non-resource
filter re-admitting a mid-priority resident) is covered by the exact
verify: on divergence the per-node host scan (`_select_victims`) answers.
"""

from __future__ import annotations

import random

from typing import Mapping

import numpy as np

from kubernetes_tpu.scheduler.framework import (
    CycleState,
    Plugin,
    Status,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
)
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot


class _WaveState:
    """Dense victim-relaxation tensors for one snapshot generation.

    Arrays (N nodes × Kmax victim prefix × R resources):
    - rel[n, k, r]: resources released by evicting the k+1 lowest-priority
      unclaimed residents of node n
    - vprio[n, k]: priority of the k-th victim (asc); INT_MAX padding
    - vsum/vmax[n, k]: priority prefix sums / maxima
    - used[n, r] / alloc[n, r], pods_used/alloc[n]
    """

    __slots__ = ("nodes", "resources", "r_index", "rel", "vprio", "vsum",
                 "vmax", "vcount", "used", "alloc", "pods_used",
                 "pods_alloc", "victims", "generation")

    INF = np.iinfo(np.int64).max

    def __init__(self, snapshot: Snapshot, claimed: set[str],
                 promised: dict[str, list[dict]]):
        nodes = list(snapshot.nodes)
        self.nodes = nodes
        self.generation = getattr(snapshot, "generation", None)
        res: dict[str, None] = {}
        for ni in nodes:
            for r in ni.allocatable.res:
                res.setdefault(r)
        self.resources = list(res)
        self.r_index = {r: j for j, r in enumerate(self.resources)}
        N, R = len(nodes), len(self.resources)
        kmax = 1
        per_node: list[list[PodInfo]] = []
        for ni in nodes:
            cand = sorted(
                (p for p in ni.pods if p.key not in claimed),
                key=lambda p: (p.priority, p.key))
            per_node.append(cand)
            kmax = max(kmax, len(cand))
        self.victims = per_node
        self.rel = np.zeros((N, kmax, R), dtype=np.int64)
        self.vprio = np.full((N, kmax), self.INF, dtype=np.int64)
        self.vsum = np.zeros((N, kmax), dtype=np.int64)
        self.vmax = np.zeros((N, kmax), dtype=np.int64)
        self.vcount = np.zeros((N,), dtype=np.int64)
        self.used = np.zeros((N, R), dtype=np.int64)
        self.alloc = np.zeros((N, R), dtype=np.int64)
        self.pods_used = np.zeros((N,), dtype=np.int64)
        self.pods_alloc = np.zeros((N,), dtype=np.int64)
        for n, ni in enumerate(nodes):
            for r, v in ni.requested.res.items():
                j = self.r_index.get(r)
                if j is not None:
                    self.used[n, j] = v
            for r, v in ni.allocatable.res.items():
                self.alloc[n, self.r_index[r]] = v
            self.pods_used[n] = ni.requested.pods
            self.pods_alloc[n] = ni.allocatable.pods
            # Unbound-but-promised preemptors charge their target node.
            for q, _ts in (promised.get(ni.name) or {}).values():
                for r, v in q.items():
                    j = self.r_index.get(r)
                    if j is not None:
                        self.used[n, j] += v
                self.pods_used[n] += 1
            cand = per_node[n]
            self.vcount[n] = len(cand)
            acc = np.zeros((R,), dtype=np.int64)
            psum = 0
            pmax = 0
            for k, p in enumerate(cand):
                for r, v in p.requests.items():
                    j = self.r_index.get(r)
                    if j is not None:
                        acc[j] += v
                psum += p.priority
                pmax = max(pmax, p.priority)
                self.rel[n, k] = acc
                self.vprio[n, k] = p.priority
                self.vsum[n, k] = psum
                self.vmax[n, k] = pmax

    def candidates(self, pod: PodInfo,
                   banned: set[int]) -> list[tuple[int, int]]:
        """[(node index, victim count)] sorted by the reference cost
        ordering — each entry is the MINIMAL victim prefix on that node
        that fits the pod (resources + pod count), victims restricted to
        priorities below the preemptor's."""
        N, kmax, R = self.rel.shape
        q = np.zeros((R,), dtype=np.int64)
        for r, v in pod.requests.items():
            j = self.r_index.get(r)
            if j is not None:
                q[j] = v
        # eligible[n, k]: prefix k+1 consists solely of lower-prio victims
        eligible = self.vprio < pod.priority
        fits = np.all(
            self.used[:, None, :] - self.rel + q[None, None, :]
            <= self.alloc[:, None, :], axis=-1)
        fits &= (self.pods_used[:, None] - (np.arange(kmax)[None, :] + 1)
                 + 1 <= self.pods_alloc[:, None])
        ok = eligible & fits
        any_ok = ok.any(axis=1)
        if banned:
            for n in banned:
                any_ok[n] = False
        idxs = np.nonzero(any_ok)[0]
        if idxs.size == 0:
            return []
        kmin = ok[idxs].argmax(axis=1)  # first fitting prefix per node
        vmax = self.vmax[idxs, kmin]
        vsum = self.vsum[idxs, kmin]
        order = np.lexsort((idxs, kmin + 1, vsum, vmax))
        return [(int(idxs[i]), int(kmin[i]) + 1) for i in order]

    def claim(self, n: int, count: int, pod: PodInfo,
              claimed: set[str], promised: dict) -> list[PodInfo]:
        """Commit a choice: mark victims claimed, charge the preemptor,
        and refresh node n's tensors IN PLACE (O(K·R)) — a full rebuild
        per preemptor made 1000-node waves O(wave² ) in python loops."""
        import time
        victims = self.victims[n][:count]
        for v in victims:
            claimed.add(v.key)
        promised.setdefault(self.nodes[n].name, {})[pod.key] = (
            dict(pod.requests), time.monotonic())
        # Victims leave, the preemptor's load lands.
        remaining = self.victims[n][count:]
        self.victims[n] = remaining
        for v in victims:
            for r, val in v.requests.items():
                j = self.r_index.get(r)
                if j is not None:
                    self.used[n, j] -= val
        for r, val in pod.requests.items():
            j = self.r_index.get(r)
            if j is not None:
                self.used[n, j] += val
        self.pods_used[n] += 1 - count
        self.rel[n] = 0
        self.vprio[n] = self.INF
        self.vsum[n] = 0
        self.vmax[n] = 0
        self.vcount[n] = len(remaining)
        acc = np.zeros((self.rel.shape[2],), dtype=np.int64)
        psum = 0
        pmax = 0
        for k, p in enumerate(remaining):
            for r, val in p.requests.items():
                j = self.r_index.get(r)
                if j is not None:
                    acc[j] += val
            psum += p.priority
            pmax = max(pmax, p.priority)
            self.rel[n, k] = acc
            self.vprio[n, k] = p.priority
            self.vsum[n, k] = psum
            self.vmax[n, k] = pmax
        return list(victims)


class DefaultPreemption(Plugin):
    NAME = "DefaultPreemption"
    EXTENSION_POINTS = ("PostFilter",)

    def __init__(self, args=None, framework=None, evict=None):
        """`framework` runs the Filter dry-runs; `evict(pod_key, victim_keys,
        node)` is the side-effect callback the scheduler injects (API deletes
        + nominatedNodeName patch happen there)."""
        super().__init__(args)
        self.framework = framework
        self.evict = evict
        self._rng = random.Random(self.args.get("seed", 0))
        #: wave tensors: kept across a preemption wave with in-place claim
        #: updates; resynced to the live snapshot on a budget (claims are
        #: exact in-wave, external drift is caught by the live verify).
        self._wave: _WaveState | None = None
        self._wave_claims = 0
        self._wave_built = 0.0
        #: victim keys promised to earlier preemptors; pruned when the
        #: victim is no longer resident (its deletion landed).
        self._claimed: set[str] = set()
        #: node name -> {preemptor pod key -> (requests, promised-at)};
        #: entries drop when the pod binds (appears among residents), when
        #: it re-nominates elsewhere, or on TTL (pod deleted pre-bind).
        self._promised: dict[str, dict[str, tuple]] = {}
        self._promised_pods: dict[str, str] = {}  # pod key -> node name

    def post_filter(self, state: CycleState, pod: PodInfo, snapshot: Snapshot,
                    filtered_status: Mapping[str, Status]) -> tuple[str, Status]:
        if self.framework is None:
            return "", Status.unschedulable()
        wave = self._wave_state(snapshot)
        banned: set[int] = set()
        # Nodes rejected as UnschedulableAndUnresolvable can't be helped by
        # preemption (preemption.go `nodesWherePreemptionMightHelp`).
        for n, ni in enumerate(wave.nodes):
            st = filtered_status.get(ni.name)
            if st is not None and st.code == UNSCHEDULABLE_AND_UNRESOLVABLE:
                banned.add(n)
        ranked = wave.candidates(pod, banned)
        # Seeded tie shuffle among equal-cost leaders (the reference scans
        # a Go map whose iteration order is randomized, which spreads
        # concurrent preemptors across equal-cost nodes — a deterministic
        # first-min made every preemptor in a wave nominate the SAME node
        # and retry quadratically).
        if len(ranked) > 1:
            lead_cost = self._cost_of(wave, ranked[0])
            tie_end = 1
            while tie_end < len(ranked) and \
                    self._cost_of(wave, ranked[tie_end]) == lead_cost:
                tie_end += 1
            head = ranked[:tie_end]
            self._rng.shuffle(head)
            ranked = head + ranked[tie_end:]
        # Exact verify on the chosen candidate only; on divergence (a
        # non-resource filter still failing), try the next best, then the
        # per-node host scan.
        for attempt, (n, count) in enumerate(ranked):
            if attempt >= 8:
                break
            ni = wave.nodes[n]
            victims = wave.victims[n][:count]
            # Verify against the LIVE node (the wave may be a bounded-age
            # batch view): stale-wave mis-rankings fail here and fall to
            # the next-best candidate.
            live_ni = snapshot.get(ni.name) or ni
            dry = live_ni.clone()
            for v in victims:
                dry.remove_pod(v.key)
            if self.framework.run_filters(
                    state.clone(), pod, dry).is_success():
                self._drop_promise(pod.key)  # re-nomination moves the charge
                chosen = wave.claim(n, count, pod, self._claimed,
                                    self._promised)
                self._promised_pods[pod.key] = ni.name
                self._wave_claims += 1
                if self.evict is not None:
                    self.evict(pod, [v.key for v in chosen], ni.name)
                return ni.name, Status.success()
        return self._post_filter_scan(state, pod, snapshot, filtered_status)

    @staticmethod
    def _cost_of(wave: _WaveState, entry: tuple[int, int]):
        n, count = entry
        return (int(wave.vmax[n, count - 1]), int(wave.vsum[n, count - 1]),
                count)

    #: resync budget: rebuild from the live snapshot after this many
    #: claims or this much wall time, whichever first.
    WAVE_MAX_CLAIMS = 128
    WAVE_MAX_AGE_S = 0.5
    #: a nominated preemptor that never binds stops being charged.
    PROMISE_TTL_S = 30.0

    def _drop_promise(self, pod_key: str) -> None:
        node = self._promised_pods.pop(pod_key, None)
        if node is not None:
            entries = self._promised.get(node)
            if entries is not None:
                entries.pop(pod_key, None)
                if not entries:
                    self._promised.pop(node, None)

    def _wave_state(self, snapshot: Snapshot) -> _WaveState:
        import time
        wave = self._wave
        if wave is not None and len(wave.nodes) == len(snapshot.nodes) \
                and self._wave_claims < self.WAVE_MAX_CLAIMS \
                and time.monotonic() - self._wave_built < self.WAVE_MAX_AGE_S:
            return wave
        # Prune ledgers against live residency before rebuilding: a
        # claimed victim still resident keeps its claim (delete in
        # flight); one that vanished is done. A promised preemptor that
        # bound is now a resident and stops being charged separately;
        # one that never binds (deleted pre-bind) ages out on TTL.
        resident: set[str] = set()
        for ni in snapshot.nodes:
            for p in ni.pods:
                resident.add(p.key)
        self._claimed &= resident
        now = time.monotonic()
        for node in list(self._promised):
            entries = self._promised[node]
            for pk in list(entries):
                _reqs, ts = entries[pk]
                if pk in resident or now - ts > self.PROMISE_TTL_S:
                    entries.pop(pk, None)
                    self._promised_pods.pop(pk, None)
            if not entries:
                self._promised.pop(node, None)
        wave = _WaveState(snapshot, self._claimed, self._promised)
        self._wave = wave
        self._wave_claims = 0
        self._wave_built = time.monotonic()
        return wave

    # -- legacy exact scan (fallback + differential reference) -------------

    def _post_filter_scan(self, state: CycleState, pod: PodInfo,
                          snapshot: Snapshot,
                          filtered_status: Mapping[str, Status]
                          ) -> tuple[str, Status]:
        candidates: list[tuple[str, list[PodInfo]]] = []
        for node in snapshot:
            st = filtered_status.get(node.name)
            if st is not None and st.code == UNSCHEDULABLE_AND_UNRESOLVABLE:
                continue
            victims = self._select_victims(state, pod, node)
            if victims is not None:
                candidates.append((node.name, victims))
        if not candidates:
            return "", Status.unschedulable(
                "preemption: 0/%d nodes are available" % len(snapshot))
        import time
        node_name, victims = self._pick_one(candidates)
        for v in victims:
            self._claimed.add(v.key)
        self._drop_promise(pod.key)
        self._promised.setdefault(node_name, {})[pod.key] = (
            dict(pod.requests), time.monotonic())
        self._promised_pods[pod.key] = node_name
        self._wave = None
        if self.evict is not None:
            self.evict(pod, [v.key for v in victims], node_name)
        return node_name, Status.success()

    def _select_victims(self, state: CycleState, pod: PodInfo,
                        node: NodeInfo) -> list[PodInfo] | None:
        """Dry-run: remove ALL lower-priority pods; if pod fits, add back as
        many as possible (highest priority first), keeping feasibility."""
        lower = [p for p in node.pods
                 if p.priority < pod.priority and p.key not in self._claimed]
        if not lower:
            return None
        dry = node.clone()
        for v in lower:
            dry.remove_pod(v.key)
        if not self.framework.run_filters(state.clone(), pod, dry).is_success():
            return None
        # Reprieve pass: re-add in priority-desc order while still feasible.
        victims: list[PodInfo] = []
        for v in sorted(lower, key=lambda p: -p.priority):
            dry.add_pod(v)
            if self.framework.run_filters(state.clone(), pod, dry).is_success():
                continue  # reprieved
            dry.remove_pod(v.key)
            victims.append(v)
        return victims if victims else None

    def _pick_one(self, candidates: list[tuple[str, list[PodInfo]]]
                  ) -> tuple[str, list[PodInfo]]:
        """pickOneNodeForPreemption cost ordering (no PDB tier yet —
        disruption controller integration adds it)."""
        def cost(entry):
            _, victims = entry
            return (
                max((v.priority for v in victims), default=0),
                sum(v.priority for v in victims),
                len(victims),
            )
        best = min(cost(e) for e in candidates)
        ties = [e for e in candidates if cost(e) == best]
        return ties[self._rng.randrange(len(ties))]
