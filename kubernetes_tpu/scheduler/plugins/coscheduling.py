"""Coscheduling: PodGroup gang scheduling via the Permit barrier.

Parity target: sigs.k8s.io/scheduler-plugins coscheduling (SURVEY §2.3
"out-of-tree but in-scope"): pods labeled with a PodGroup wait at Permit
until `minMember` siblings have reserved; then the whole gang is released
to bind. A gang that can't assemble before `scheduleTimeoutSeconds` is
rejected wholesale (each waiter times out and requeues — all-or-nothing).

PodGroup objects live in the store as a `podgroups` resource:
    {"metadata": {...}, "spec": {"minMember": N, "scheduleTimeoutSeconds": S}}
Pods join via the `scheduling.x-k8s.io/pod-group` label.

PreEnqueue additionally gates pods of groups that don't yet have minMember
pods created (the plugin's own PreEnqueue behavior) — avoids burning cycles
scheduling a gang that cannot possibly assemble.

The TPU batched path composes naturally: the solver assigns the whole batch,
then each pod's Permit runs — a complete gang in one batch sails through the
barrier in one cycle (the "batched all-or-nothing assignment" the north star
names as the Sinkhorn/EP analog).
"""

from __future__ import annotations

import logging
from collections import defaultdict

from kubernetes_tpu.scheduler.framework import CycleState, Plugin, Status
from kubernetes_tpu.scheduler.types import PodInfo

logger = logging.getLogger(__name__)

POD_GROUP_LABEL = "scheduling.x-k8s.io/pod-group"
DEFAULT_SCHEDULE_TIMEOUT = 10.0


def _pod_group_index(obj: dict) -> list[str]:
    name = (obj.get("metadata", {}).get("labels") or {}).get(POD_GROUP_LABEL)
    if not name:
        return []
    ns = obj.get("metadata", {}).get("namespace", "default")
    return [f"{ns}/{name}"]


def make_pod_group(name: str, min_member: int, namespace: str = "default",
                   schedule_timeout_seconds: float | None = None,
                   slice_shape: list | tuple | None = None) -> dict:
    from kubernetes_tpu.api.meta import new_object
    spec = {"minMember": min_member}
    if schedule_timeout_seconds is not None:
        spec["scheduleTimeoutSeconds"] = schedule_timeout_seconds
    if slice_shape is not None:
        # Slice-shaped gang (topology/): members must land on one
        # contiguous sub-mesh of this shape (TopologySlice plans it,
        # Permit here enforces it before release).
        spec["sliceShape"] = [int(s) for s in slice_shape]
    return new_object("PodGroup", name, namespace, spec=spec)


class Coscheduling(Plugin):
    NAME = "Coscheduling"
    EXTENSION_POINTS = ("PreEnqueue", "Permit", "PostBind", "Reserve")
    EVENTS = ["Pod/Add", "Pod/Delete"]

    def __init__(self, args=None):
        super().__init__(args)
        #: group key -> pod keys currently parked at Permit
        self._waiting: dict[str, set[str]] = defaultdict(set)
        #: group key -> pod keys bound (left the barrier)
        self._bound: dict[str, set[str]] = defaultdict(set)
        #: group key -> {pod key -> reserved node} — the membership the
        #: sliceShape contiguity check at Permit verifies.
        self._nodes: dict[str, dict[str, str]] = defaultdict(dict)
        self.scheduler = None      # wired by Scheduler (allow/reject handles)
        self.pg_informer = None    # wired via set_informers
        self.pod_informer = None
        self.node_informer = None  # node labels for the coordinate map

    def set_scheduler(self, scheduler) -> None:
        self.scheduler = scheduler

    def set_informers(self, factory) -> None:
        import asyncio

        from kubernetes_tpu.client import ResourceEventHandler

        self.pg_informer = factory.informer("podgroups")
        self.pod_informer = factory.informer("pods")
        self.node_informer = factory.informer("nodes")
        # O(1) sibling counts for pre_enqueue (vs scanning every pod).
        self.pod_informer.indexer.add_indexer("podgroup", _pod_group_index)

        def on_pod_delete(obj):
            # Gang membership must not survive pod deletion: stale _bound
            # entries would let a reused group name bypass the barrier.
            name = (obj.get("metadata", {}).get("labels") or {}) \
                .get(POD_GROUP_LABEL)
            if not name:
                return
            ns = obj["metadata"].get("namespace", "default")
            key = (f"{ns}/{obj['metadata']['name']}")
            self._bound[f"{ns}/{name}"].discard(key)
            self._waiting[f"{ns}/{name}"].discard(key)
            self._nodes[f"{ns}/{name}"].pop(key, None)

        self.pod_informer.add_event_handler(ResourceEventHandler(
            on_delete=on_pod_delete))

        def on_pg_change(obj):
            # A PodGroup arriving/changing can lift gates of already-parked
            # pods — surface it to the queue as a cluster event.
            if self.scheduler is not None:
                from kubernetes_tpu.scheduler.queue import ClusterEvent
                asyncio.ensure_future(self.scheduler.queue.move_all(
                    ClusterEvent("PodGroup", "Add")))

        self.pg_informer.add_event_handler(ResourceEventHandler(
            on_add=on_pg_change, on_update=lambda o, n: on_pg_change(n)))

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def group_key(pod: PodInfo) -> str | None:
        name = pod.labels.get(POD_GROUP_LABEL)
        return f"{pod.namespace}/{name}" if name else None

    def _pod_group(self, group_key: str) -> dict | None:
        if self.pg_informer is None:
            return None
        return self.pg_informer.indexer.get(group_key)

    def _group_pod_count(self, group_key: str) -> int:
        if self.pod_informer is None:
            return 0
        return len(self.pod_informer.indexer.by_index("podgroup", group_key))

    # -- extension points --------------------------------------------------

    def pre_enqueue(self, pod: PodInfo) -> Status:
        gk = self.group_key(pod)
        if gk is None:
            return Status.success()
        pg = self._pod_group(gk)
        if pg is None:
            return Status.unschedulable(
                f"PodGroup {gk} not found", resolvable=False)
        min_member = int(pg["spec"].get("minMember", 1))
        if self._group_pod_count(gk) < min_member:
            return Status.unschedulable(
                f"gang {gk}: fewer than minMember={min_member} pods exist")
        return Status.success()

    def _slice_misaligned(self, gk: str, pg: dict) -> str | None:
        """Reason the assembled gang's reserved nodes do NOT form one
        contiguous sub-mesh of the group's sliceShape; None = aligned
        (or not a slice-shaped gang / topology off — count-only gangs
        keep the pre-topology barrier exactly)."""
        from kubernetes_tpu.topology.mesh import (
            node_cell, normalize_shape, parse_mesh_shape)
        from kubernetes_tpu.topology.slices import is_contiguous_slice
        from kubernetes_tpu.utils import flags

        raw = pg["spec"].get("sliceShape")
        if not raw or not flags.get("KTPU_TOPOLOGY"):
            return None
        try:
            shape = normalize_shape(raw)
        except (ValueError, TypeError):
            return None  # malformed shape: count-only semantics
        if self.node_informer is None:
            return "no node informer for the slice contiguity check"
        members = self._nodes.get(gk, {})
        node_names = set(members.values())
        if len(node_names) < len(members):
            return "two slice members reserved the same node"
        all_nodes = self.node_informer.indexer.list()
        spec = parse_mesh_shape(
            flags.get("KTPU_MESH_SHAPE"), len(all_nodes))
        cells = []
        for name in node_names:
            obj = self.node_informer.indexer.get(name)
            labels = (obj or {}).get("metadata", {}).get("labels") or {}
            cell = node_cell(name, labels, spec)
            if cell is None:
                return f"member node {name} is off-mesh"
            cells.append(cell)
        if not is_contiguous_slice(cells, spec, shape):
            return ("reserved nodes do not form a contiguous "
                    f"{'x'.join(str(s) for s in raw)} sub-mesh")
        return None

    def reserve(self, state: CycleState, pod: PodInfo,
                node_name: str) -> Status:
        gk = self.group_key(pod)
        if gk is not None:
            self._nodes[gk][pod.key] = node_name
        return Status.success()

    def permit(self, state: CycleState, pod: PodInfo,
               node_name: str) -> tuple[Status, float]:
        gk = self.group_key(pod)
        if gk is None:
            return Status.success(), 0.0
        pg = self._pod_group(gk)
        if pg is None:
            return Status.unschedulable(f"PodGroup {gk} vanished"), 0.0
        min_member = int(pg["spec"].get("minMember", 1))
        assembled = (len(self._waiting[gk]) + len(self._bound[gk]) + 1)
        if assembled >= min_member:
            misaligned = self._slice_misaligned(gk, pg)
            if misaligned is not None:
                # A complete but BENT gang must not bind: reject the
                # whole membership (all-or-nothing) so the next attempt
                # replans from a fresh TopologySlice placement.
                waiting = self._waiting.pop(gk, set())
                if self.scheduler is not None:
                    for key in waiting:
                        self.scheduler.reject_waiting_pod(key)
                logger.info("gang %s: %s; rejecting %d waiters",
                            gk, misaligned, len(waiting))
                return Status.unschedulable(
                    f"gang {gk}: {misaligned}"), 0.0
            # Gang complete: release every parked sibling.
            waiting = self._waiting.pop(gk, set())
            if self.scheduler is not None:
                for key in waiting:
                    self.scheduler.allow_waiting_pod(key)
            self._bound[gk].update(waiting)
            self._bound[gk].add(pod.key)
            if pg["spec"].get("sliceShape") and self.scheduler is not None \
                    and getattr(self.scheduler, "metrics", None) is not None:
                self.scheduler.metrics.slice_gangs_bound.inc()
            return Status.success(), 0.0
        self._waiting[gk].add(pod.key)
        timeout = float(pg["spec"].get("scheduleTimeoutSeconds",
                                       DEFAULT_SCHEDULE_TIMEOUT))
        return Status.wait(), timeout

    def unreserve(self, state: CycleState, pod: PodInfo, node_name: str) -> None:
        """A gang member failed downstream (or timed out at Permit):
        reject the rest of the gang — all-or-nothing."""
        gk = self.group_key(pod)
        if gk is None:
            return
        self._waiting[gk].discard(pod.key)
        self._bound[gk].discard(pod.key)
        self._nodes[gk].pop(pod.key, None)
        waiting = self._waiting.pop(gk, set())
        if waiting and self.scheduler is not None:
            logger.info("gang %s: member %s failed; rejecting %d waiters",
                        gk, pod.key, len(waiting))
            for key in waiting:
                self.scheduler.reject_waiting_pod(key)

    def post_bind(self, state: CycleState, pod: PodInfo, node_name: str) -> None:
        gk = self.group_key(pod)
        if gk is not None:
            self._bound[gk].add(pod.key)
