"""PodTopologySpread: maxSkew constraints over topology domains.

Parity target: pkg/scheduler/framework/plugins/podtopologyspread/
{plugin.go,filtering.go,scoring.go}:

- Filter (whenUnsatisfiable=DoNotSchedule): placing the pod on a node must
  keep `count(domain_of(node)) + selfMatch - min(count over eligible
  domains) <= maxSkew` for every constraint (selfMatch = 1 iff the
  constraint's selector + namespace set match the pod itself).
- minDomains: when fewer eligible domains exist than minDomains, the
  global minimum is treated as 0 (k8s MinDomainsInPodTopologySpread).
- namespaceSelector (extension beyond the reference's spread API): a
  constraint may widen counting beyond the pod's own namespace, resolved
  exactly like an affinity term's namespaceSelector
  (interpodaffinity.resolve_term_namespaces; {} = every namespace).
- Score (whenUnsatisfiable=ScheduleAnyway): lower resulting skew → higher.
- Default constraints (SystemDefaulting): maxSkew=3 on hostname /
  maxSkew=5 on zone, ScheduleAnyway — applied when the pod has none.

Domains: nodes missing the topologyKey are ignored entirely (not eligible).
nodeAffinityPolicy/nodeTaintsPolicy default to Honor: domains are counted
only over nodes the pod could run on per nodeSelector/affinity and taints.
"""

from __future__ import annotations

from collections import defaultdict

from kubernetes_tpu.api.labels import (
    from_label_selector,
    match_node_selector_terms,
    ns_contains,
)
from kubernetes_tpu.api.types import (
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    find_untolerated_taint,
)
from kubernetes_tpu.scheduler.framework import (
    MAX_NODE_SCORE,
    CycleState,
    Plugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot

_STATE_KEY = "PreFilterPodTopologySpread"

HOSTNAME = "kubernetes.io/hostname"
ZONE = "topology.kubernetes.io/zone"

DEFAULT_CONSTRAINTS = [
    {"maxSkew": 3, "topologyKey": HOSTNAME, "whenUnsatisfiable": "ScheduleAnyway"},
    {"maxSkew": 5, "topologyKey": ZONE, "whenUnsatisfiable": "ScheduleAnyway"},
]


def _node_eligible(pod: PodInfo, node: NodeInfo) -> bool:
    """Honor nodeAffinity + taints when counting domains (filtering.go
    `pl.filterNodesWithTaintsAndAffinity` equivalent)."""
    if not node.node:
        return False
    for k, v in pod.node_selector.items():
        if node.labels.get(k) != v:
            return False
    na = pod.affinity.get("nodeAffinity") or {}
    required = na.get("requiredDuringSchedulingIgnoredDuringExecution")
    if required:
        if not match_node_selector_terms(
                required.get("nodeSelectorTerms") or [], node.labels, node.name):
            return False
    if find_untolerated_taint(node.taints, pod.tolerations,
                              (TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE)) is not None:
        return False
    return True


class _SpreadState:
    __slots__ = ("constraints", "counts", "mins", "self_match")

    def __init__(self):
        self.constraints: list[dict] = []
        # per-constraint-index: {topologyValue: matching pod count}
        self.counts: list[dict[str, int]] = []
        self.mins: list[int] = []
        # per-constraint-index: 1 if the constraint's selector matches the
        # incoming pod's own labels (filtering.go selfMatchNum), else 0
        self.self_match: list[int] = []


class PodTopologySpread(Plugin):
    NAME = "PodTopologySpread"
    EXTENSION_POINTS = ("PreFilter", "Filter", "PreScore", "Score")
    EVENTS = ["Pod/Add", "Pod/Delete", "Node/Add", "Node/Update"]

    def __init__(self, args=None):
        super().__init__(args)
        self.default_constraints = self.args.get("defaultConstraints")
        if self.default_constraints is None and self.args.get(
                "defaultingType", "System") == "System":
            self.default_constraints = DEFAULT_CONSTRAINTS
        # namespaceSelector constraints resolve like affinity terms
        # (shared NamespaceResolver; informer-less it still gives the
        # static {}-is-everything semantics).
        from kubernetes_tpu.scheduler.plugins.interpodaffinity import (
            NamespaceResolver,
        )
        self.ns_resolver = NamespaceResolver()

    def set_informers(self, factory) -> None:
        self.ns_resolver.wire(factory)

    def constraint_namespaces(self, c: dict, pod_ns: str) -> tuple:
        """A constraint's effective namespace set (ALL_NAMESPACES-aware);
        plain constraints count within the pod's own namespace."""
        from kubernetes_tpu.scheduler.plugins.interpodaffinity import (
            resolve_term_namespaces,
        )
        return resolve_term_namespaces(c, pod_ns, self.ns_resolver)

    def _constraints_for(self, pod: PodInfo, action: str) -> list[dict]:
        cons = pod.topology_spread_constraints
        if not cons and self.default_constraints:
            # Default constraints adopt the pod's own labels as selector (the
            # reference builds the selector from the pod's owning service/RS;
            # we use pod labels — same effect for replicated workloads).
            cons = [
                {**c, "labelSelector": {"matchLabels": pod.labels}}
                for c in self.default_constraints
            ] if pod.labels else []
        return [c for c in cons if c.get("whenUnsatisfiable", "DoNotSchedule") == action]

    def _build_state(self, pod: PodInfo, nodes, action: str) -> _SpreadState:
        s = _SpreadState()
        s.constraints = self._constraints_for(pod, action)
        for c in s.constraints:
            tk = c["topologyKey"]
            sel = from_label_selector(c.get("labelSelector"))
            nses = self.constraint_namespaces(c, pod.namespace)
            counts: dict[str, int] = defaultdict(int)
            for node in nodes:
                tv = node.labels.get(tk)
                if tv is None or not _node_eligible(pod, node):
                    continue
                counts.setdefault(tv, 0)
                for existing in node.pods:
                    if ns_contains(nses, existing.namespace) \
                            and sel.matches(existing.labels):
                        counts[tv] += 1
            s.counts.append(dict(counts))
            # minDomains (DoNotSchedule only in the API; harmless on the
            # score path, which never reads mins): fewer eligible domains
            # than minDomains → global minimum is 0.
            md = int(c.get("minDomains") or 0)
            if md and len(counts) < md:
                s.mins.append(0)
            else:
                s.mins.append(min(counts.values()) if counts else 0)
            s.self_match.append(
                1 if ns_contains(nses, pod.namespace)
                and sel.matches(pod.labels) else 0)
        return s

    # -- Filter path -------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: PodInfo, snapshot: Snapshot) -> Status:
        s = self._build_state(pod, snapshot, "DoNotSchedule")
        if not s.constraints:
            return Status.skip()
        state.write(_STATE_KEY, s)
        return Status.success()

    def filter(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> Status:
        s: _SpreadState | None = state.read(_STATE_KEY)
        if s is None:
            return Status.success()
        for i, c in enumerate(s.constraints):
            tk = c["topologyKey"]
            tv = node.labels.get(tk)
            if tv is None:
                return Status.unschedulable(
                    "node(s) didn't have the requested topology key",
                    resolvable=False)
            count = s.counts[i].get(tv)
            if count is None:
                continue  # node domain not eligible — treated as fresh
            if count + s.self_match[i] - s.mins[i] > c.get("maxSkew", 1):
                return Status.unschedulable(
                    "node(s) didn't match pod topology spread constraints")
        return Status.success()

    # -- Score path --------------------------------------------------------

    def pre_score(self, state: CycleState, pod: PodInfo, nodes: list[NodeInfo]) -> Status:
        s = self._build_state(pod, nodes, "ScheduleAnyway")
        if not s.constraints:
            return Status.skip()
        state.write(_STATE_KEY + "/score", s)
        return Status.success()

    def score(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> float:
        s: _SpreadState | None = state.read(_STATE_KEY + "/score")
        if s is None:
            return 0.0
        total = 0.0
        for i, c in enumerate(s.constraints):
            tv = node.labels.get(c["topologyKey"])
            if tv is None:
                continue
            total += s.counts[i].get(tv, 0)
        return total  # raw: matching-pod count in this node's domains

    def normalize_scores(self, state: CycleState, pod: PodInfo,
                         scores: dict[str, float]) -> None:
        """Lower count → higher score (scoring.go NormalizeScore)."""
        if not scores:
            return
        mx = max(scores.values())
        mn = min(scores.values())
        spread = mx - mn
        for k, v in scores.items():
            scores[k] = MAX_NODE_SCORE * (mx - v) / spread if spread else float(MAX_NODE_SCORE)
