"""Volume plugin family: VolumeBinding, VolumeZone, NodeVolumeLimits.

Parity target: pkg/scheduler/framework/plugins/volumebinding/ (SURVEY §2.3:
"PVC↔PV topology feasibility; PreBind blocks on actual provisioning"),
volumezone/, nodevolumelimits/. VolumeBinding is the one in-tree plugin
exercising the full Reserve/Unreserve seam and a genuinely blocking
PreBind: at Reserve it stakes the claim→node choice (selected-node
annotation plan), at PreBind it writes the annotation and BLOCKS until the
PV controller has bound/provisioned every claim (WaitForFirstConsumer).
"""

from __future__ import annotations

import asyncio
import logging

from kubernetes_tpu.api.meta import namespaced_name
from kubernetes_tpu.controllers.pvbinder import SELECTED_NODE_ANN
from kubernetes_tpu.scheduler.framework import CycleState, Plugin, Status
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot
from kubernetes_tpu.store.mvcc import StoreError

logger = logging.getLogger(__name__)

_STATE_KEY = "VolumeBinding/claims"
ZONE_LABELS = ("topology.kubernetes.io/zone", "topology.kubernetes.io/region")


class _PodVolumeClaims:
    """PreFilter result: the pod's claims, partitioned (PodVolumes in the
    reference)."""

    __slots__ = ("bound", "unbound_wffc", "unbound_immediate")

    def __init__(self):
        self.bound: list[dict] = []           # PVC objects with volumeName
        self.unbound_wffc: list[dict] = []    # wait-for-first-consumer
        self.unbound_immediate: list[dict] = []


class VolumeBinding(Plugin):
    NAME = "VolumeBinding"
    EXTENSION_POINTS = ("PreFilter", "Filter", "Reserve", "PreBind")
    # EventsToRegister parity: PVC/PV/StorageClass changes can make a pod
    # rejected for volume reasons schedulable again.
    EVENTS = ["Pod/Delete", "Node/Add", "Node/Update",
              "PersistentVolumeClaim/Add", "PersistentVolumeClaim/Update",
              "PersistentVolume/Add", "PersistentVolume/Update",
              "StorageClass/Add"]

    def __init__(self, args=None):
        super().__init__(args)
        #: PreBind provisioning wait (volumebinding bindTimeout, 600s
        #: upstream; short here — simulated provisioners are fast).
        self.bind_timeout = float(self.args.get("bindTimeoutSeconds", 30.0))
        self.store = None
        self._pvc_informer = None
        self._pv_informer = None
        self._sc_informer = None

    def set_informers(self, factory) -> None:
        self._pvc_informer = factory.informer("persistentvolumeclaims")
        self._pv_informer = factory.informer("persistentvolumes")
        self._sc_informer = factory.informer("storageclasses")

    def set_scheduler(self, sched) -> None:
        self.store = sched.store

    # -- PreFilter: load + partition the pod's claims ----------------------

    def _get_pvc(self, namespace: str, name: str) -> dict | None:
        if self._pvc_informer is None:
            return None
        return self._pvc_informer.indexer.get(f"{namespace}/{name}")

    def _binding_mode(self, pvc: dict) -> str:
        sc_name = pvc.get("spec", {}).get("storageClassName")
        if sc_name and self._sc_informer is not None:
            sc = self._sc_informer.indexer.get(sc_name)
            if sc is not None:
                return sc.get("volumeBindingMode", "Immediate")
        return "Immediate"

    def pre_filter(self, state: CycleState, pod: PodInfo,
                   snapshot: Snapshot) -> Status:
        if not pod.pvc_names:
            return Status.skip()
        if self._pvc_informer is None:
            # No informer wiring (pure unit harnesses): nothing to check.
            return Status.skip()
        claims = _PodVolumeClaims()
        for name in pod.pvc_names:
            pvc = self._get_pvc(pod.namespace, name)
            if pvc is None:
                return Status.unschedulable(
                    f'persistentvolumeclaim "{name}" not found',
                    resolvable=False)
            if pvc.get("spec", {}).get("volumeName"):
                claims.bound.append(pvc)
            elif self._binding_mode(pvc) == "WaitForFirstConsumer":
                claims.unbound_wffc.append(pvc)
            else:
                claims.unbound_immediate.append(pvc)
        state.write(_STATE_KEY, claims)
        return Status.success()

    # -- Filter: topology feasibility per node -----------------------------

    def _pv_of(self, pvc: dict) -> dict | None:
        vol = pvc.get("spec", {}).get("volumeName")
        if vol and self._pv_informer is not None:
            return self._pv_informer.indexer.get(vol)
        return None

    def _find_matching_pv(self, pvc: dict, node: NodeInfo) -> dict | None:
        from kubernetes_tpu.controllers.pvbinder import (
            pv_matches_claim, pv_node_ok)
        if self._pv_informer is None:
            return None
        node_obj = {"metadata": {"name": node.name, "labels": node.labels}}
        for pv in self._pv_informer.indexer.list():
            if pv_matches_claim(pv, pvc) and pv_node_ok(pv, node_obj):
                return pv
        return None

    def _provisionable(self, pvc: dict, node: NodeInfo) -> bool:
        """Dynamic-provisioning feasibility: provisioner exists and the
        class's allowedTopologies admit the node."""
        from kubernetes_tpu.controllers.pvbinder import NO_PROVISIONER
        sc_name = pvc.get("spec", {}).get("storageClassName")
        if not sc_name or self._sc_informer is None:
            return False
        sc = self._sc_informer.indexer.get(sc_name)
        if sc is None or sc.get("provisioner") == NO_PROVISIONER:
            return False
        allowed = sc.get("allowedTopologies")
        if not allowed:
            return True
        for topo in allowed:
            ok = True
            for expr in topo.get("matchLabelExpressions") or []:
                if node.labels.get(expr.get("key")) not in \
                        (expr.get("values") or []):
                    ok = False
                    break
            if ok:
                return True
        return False

    def filter(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> Status:
        from kubernetes_tpu.controllers.pvbinder import pv_node_ok
        claims: _PodVolumeClaims | None = state.read(_STATE_KEY)
        if claims is None:
            return Status.success()
        node_obj = {"metadata": {"name": node.name, "labels": node.labels}}
        for pvc in claims.bound:
            pv = self._pv_of(pvc)
            if pv is not None and not pv_node_ok(pv, node_obj):
                return Status.unschedulable(
                    "node(s) had volume node affinity conflict",
                    resolvable=False)
        if claims.unbound_immediate:
            # Immediate-mode claims are the PV controller's job; an unbound
            # one means binding hasn't happened yet (volume_binding.go
            # ErrReasonBindConflict path).
            return Status.unschedulable(
                "pod has unbound immediate PersistentVolumeClaims")
        for pvc in claims.unbound_wffc:
            if self._find_matching_pv(pvc, node) is None \
                    and not self._provisionable(pvc, node):
                return Status.unschedulable(
                    "node(s) didn't find available persistent volumes to "
                    "bind")
        return Status.success()

    # -- Reserve / Unreserve: stake the claim → node plan ------------------

    def reserve(self, state: CycleState, pod: PodInfo,
                node_name: str) -> Status:
        # AssumePodVolumes equivalent: nothing to stage host-side (the
        # binding plan is just the node choice, which pre_bind/unreserve
        # receive directly); Reserve registration exists so Unreserve runs
        # the annotation rollback on a failed cycle.
        return Status.success()

    def unreserve(self, state: CycleState, pod: PodInfo,
                  node_name: str) -> None:
        claims: _PodVolumeClaims | None = state.read(_STATE_KEY)
        if claims is None or self.store is None:
            return
        # Roll back the selected-node annotation so the claims return to
        # the waiting-for-consumer state (volume_binding.go RevertAssumed).
        for pvc in claims.unbound_wffc:
            key = namespaced_name(pvc)

            def clear(obj):
                anns = obj["metadata"].get("annotations") or {}
                if SELECTED_NODE_ANN not in anns or \
                        obj.get("spec", {}).get("volumeName"):
                    return None
                del anns[SELECTED_NODE_ANN]
                return obj
            asyncio.ensure_future(self._safe_update(key, clear))

    async def _safe_update(self, key: str, mutate) -> None:
        try:
            await self.store.guaranteed_update(
                "persistentvolumeclaims", key, mutate, return_copy=False)
        except StoreError:
            pass

    # -- PreBind: write the plan and BLOCK on real binding -----------------

    async def pre_bind(self, state: CycleState, pod: PodInfo,
                       node_name: str) -> Status:
        claims: _PodVolumeClaims | None = state.read(_STATE_KEY)
        if claims is None or not claims.unbound_wffc or self.store is None:
            return Status.success()
        keys = [namespaced_name(pvc) for pvc in claims.unbound_wffc]
        for key in keys:
            def set_node(obj):
                if obj.get("spec", {}).get("volumeName"):
                    return None
                anns = obj["metadata"].setdefault("annotations", {})
                if anns.get(SELECTED_NODE_ANN) == node_name:
                    return None
                anns[SELECTED_NODE_ANN] = node_name
                return obj
            try:
                await self.store.guaranteed_update(
                    "persistentvolumeclaims", key, set_node,
                    return_copy=False)
            except StoreError as e:
                return Status.error(f"writing selected-node: {e}")
        # BindPodVolumes: wait until the PV controller binds every claim.
        deadline = asyncio.get_event_loop().time() + self.bind_timeout
        while True:
            pending = []
            for key in keys:
                try:
                    pvc = await self.store.get("persistentvolumeclaims", key)
                except StoreError:
                    return Status.error(f"claim {key} vanished during bind")
                if not pvc.get("spec", {}).get("volumeName"):
                    pending.append(key)
            if not pending:
                return Status.success()
            if asyncio.get_event_loop().time() > deadline:
                return Status.unschedulable(
                    f"timed out waiting for PVC(s) {pending} to bind")
            await asyncio.sleep(0.02)


class VolumeZone(Plugin):
    """Filter: a bound PV labeled with a zone/region must match the node's
    topology labels (volumezone/volume_zone.go)."""

    NAME = "VolumeZone"
    EXTENSION_POINTS = ("PreFilter", "Filter")
    EVENTS = ["Node/Add", "Node/Update",
              "PersistentVolumeClaim/Update", "PersistentVolume/Add"]

    def __init__(self, args=None):
        super().__init__(args)
        self._pvc_informer = None
        self._pv_informer = None

    def set_informers(self, factory) -> None:
        self._pvc_informer = factory.informer("persistentvolumeclaims")
        self._pv_informer = factory.informer("persistentvolumes")

    def pre_filter(self, state: CycleState, pod: PodInfo,
                   snapshot: Snapshot) -> Status:
        if not pod.pvc_names or self._pvc_informer is None:
            return Status.skip()
        return Status.success()

    def filter(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> Status:
        for name in pod.pvc_names:
            pvc = self._pvc_informer.indexer.get(f"{pod.namespace}/{name}")
            if pvc is None:
                continue
            vol = pvc.get("spec", {}).get("volumeName")
            pv = self._pv_informer.indexer.get(vol) if vol else None
            if pv is None:
                continue
            for label in ZONE_LABELS:
                want = (pv["metadata"].get("labels") or {}).get(label)
                if want is not None and \
                        node.labels.get(label) not in want.split("__"):
                    return Status.unschedulable(
                        "node(s) had no available volume zone",
                        resolvable=False)
        return Status.success()


class NodeVolumeLimits(Plugin):
    """Filter: cap PV-backed volumes per node (nodevolumelimits/csi.go —
    the CSI attach-limit check; the cap comes from the node's
    `attachable-volumes-*` allocatable or the plugin arg)."""

    NAME = "NodeVolumeLimits"
    EXTENSION_POINTS = ("PreFilter", "Filter")
    EVENTS = ["Pod/Delete"]

    DEFAULT_MAX = 256

    def __init__(self, args=None):
        super().__init__(args)
        self.max_volumes = int(self.args.get("maxVolumesPerNode",
                                             self.DEFAULT_MAX))

    def pre_filter(self, state: CycleState, pod: PodInfo,
                   snapshot: Snapshot) -> Status:
        if not pod.pvc_names:
            return Status.skip()
        return Status.success()

    def _node_limit(self, node: NodeInfo) -> int:
        for rname, v in node.allocatable.res.items():
            if rname.startswith("attachable-volumes"):
                return int(v) // 1000  # quantities are milli-scaled
        return self.max_volumes

    def filter(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> Status:
        # Unique volumes, not PVC references: pods sharing a claim share one
        # attachment (csi.go dedupes by volume unique-name).
        in_use = {f"{pi.namespace}/{name}"
                  for pi in node.pods for name in pi.pvc_names}
        new = {f"{pod.namespace}/{name}" for name in pod.pvc_names} - in_use
        if len(in_use) + len(new) > self._node_limit(node):
            return Status.unschedulable(
                "node(s) exceed max volume count", resolvable=True)
        return Status.success()


class VolumeRestrictions(Plugin):
    """Volume access-mode conflicts.

    Parity target: plugins/volumerestrictions/ (SURVEY §2.3):
    - ReadWriteOncePod: a PVC with the ReadWriteOncePod access mode
      admits exactly ONE consumer pod cluster-wide; a second pod is
      unschedulable everywhere while the first exists (the reference's
      conflict count over the PreFilter-computed user set).
    - ReadWriteOnce: the volume attaches to one NODE at a time; a pod
      reusing an RWO claim already consumed by a resident pod must land
      on that pod's node (co-location allowed, cross-node attach not).
    """

    NAME = "VolumeRestrictions"
    EXTENSION_POINTS = ("PreFilter", "Filter")
    EVENTS = ["Pod/Delete", "PersistentVolumeClaim/Add",
              "PersistentVolumeClaim/Update"]

    _STATE = "VolumeRestrictions/state"

    def __init__(self, args=None):
        super().__init__(args)
        self._pvc_informer = None

    def set_informers(self, factory) -> None:
        self._pvc_informer = factory.informer("persistentvolumeclaims")

    def _access_modes(self, namespace: str, claim: str) -> list[str]:
        if self._pvc_informer is None:
            return []
        pvc = self._pvc_informer.indexer.get(f"{namespace}/{claim}")
        if pvc is None:
            return []
        return (pvc.get("spec") or {}).get("accessModes") or []

    def pre_filter(self, state: CycleState, pod: PodInfo,
                   snapshot) -> Status:
        if not pod.pvc_names:
            return Status.skip()
        rwop: list[str] = []
        rwo: list[str] = []
        for claim in pod.pvc_names:
            modes = self._access_modes(pod.namespace, claim)
            if "ReadWriteOncePod" in modes:
                rwop.append(claim)
            elif "ReadWriteOnce" in modes:
                rwo.append(claim)
        if not rwop and not rwo:
            return Status.skip()
        #: claim -> node names of resident pods already using it.
        users: dict[str, set[str]] = {}
        watched = set(rwop) | set(rwo)
        for ni in snapshot:
            for resident in ni.pods:
                if resident.namespace != pod.namespace \
                        or resident.key == pod.key:
                    continue
                for claim in resident.pvc_names:
                    if claim in watched:
                        users.setdefault(claim, set()).add(ni.name)
        for claim in rwop:
            if users.get(claim):
                return Status.unschedulable(
                    f"PVC {claim!r} has ReadWriteOncePod access mode and "
                    "is already used by another pod", resolvable=True)
        # RWO: intersect the allowed node sets of every in-use claim.
        allowed: set[str] | None = None
        for claim in rwo:
            nodes = users.get(claim)
            if not nodes:
                continue
            allowed = nodes if allowed is None else (allowed & nodes)
        state.write(self._STATE, allowed)
        return Status.success()

    def filter(self, state: CycleState, pod: PodInfo, node) -> Status:
        allowed = state.read(self._STATE)
        if allowed is None:
            return Status.success()
        if node.name in allowed:
            return Status.success()
        return Status.unschedulable(
            "node(s) unavailable: ReadWriteOnce volume is attached to "
            "another node")
