"""NodeResourcesFit + NodeResourcesBalancedAllocation.

Parity targets:
- pkg/scheduler/framework/plugins/noderesources/fit.go (`Fit`:
  PreFilter precomputes the pod's request; Filter checks
  requested + podRequest <= allocatable per resource, plus max-pods;
  `fitsRequest` returns InsufficientResource list for explainability)
- resource_allocation.go + least_allocated.go / most_allocated.go /
  requested_to_capacity_ratio.go (ScoringStrategy)
- balanced_allocation.go (score = 100 × (1 − stddev of requested fractions))

Tensorization notes: these are the north-star plugins — their batch kernels
live in ops/plugins_tpu.py and must match this host implementation bit-for-bit
on feasibility and within fp tolerance on scores (differential-tested).
"""

from __future__ import annotations

import math

from kubernetes_tpu.api.types import CPU, MEMORY
from kubernetes_tpu.scheduler.framework import (
    MAX_NODE_SCORE,
    CycleState,
    Plugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot

_STATE_KEY = "PreFilterNodeResourcesFit"


class NodeResourcesFit(Plugin):
    NAME = "NodeResourcesFit"
    EXTENSION_POINTS = ("PreFilter", "Filter", "Score")
    EVENTS = ["Node/Add", "Node/Update", "Pod/Delete"]

    def __init__(self, args=None):
        super().__init__(args)
        strategy = self.args.get("scoringStrategy") or {}
        self.strategy_type = strategy.get("type", "LeastAllocated")
        # resources to score over: [{"name": "cpu", "weight": 1}, ...]
        self.score_resources = strategy.get("resources") or [
            {"name": CPU, "weight": 1}, {"name": MEMORY, "weight": 1},
        ]
        # RequestedToCapacityRatio shape points [{utilization, score}]
        self.shape = (strategy.get("requestedToCapacityRatio") or {}).get("shape") or [
            {"utilization": 0, "score": 0},
            {"utilization": 100, "score": 10},
        ]
        self.ignored_resources = set(self.args.get("ignoredResources") or [])

    def pre_filter(self, state: CycleState, pod: PodInfo, snapshot: Snapshot) -> Status:
        state.write(_STATE_KEY, pod.requests)
        if not pod.requests and not pod.host_ports:
            # Nothing to check resource-wise, but max-pods still applies, so
            # no Skip here (the reference skips only when the pod requests
            # nothing AND no restartable init containers; it still filters
            # pod count in Filter — we keep Filter active).
            pass
        return Status.success()

    def filter(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> Status:
        reasons = insufficient_resources(pod, node, self.ignored_resources)
        if reasons:
            return Status.unschedulable(*reasons)
        return Status.success()

    def score(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> float:
        requested = node.nonzero_requested
        pod_req = pod.nonzero_requests
        total_w = 0
        acc = 0.0
        for spec in self.score_resources:
            rname, w = spec["name"], spec.get("weight", 1)
            alloc = node.allocatable.get(rname)
            if alloc <= 0:
                continue
            req = requested.get(rname) + pod_req.get(rname, 0)
            acc += w * self._score_one(req, alloc)
            total_w += w
        return acc / total_w if total_w else 0.0

    def _score_one(self, requested: int, allocatable: int) -> float:
        if requested > allocatable:
            return 0.0
        if self.strategy_type == "MostAllocated":
            return MAX_NODE_SCORE * requested / allocatable
        if self.strategy_type == "RequestedToCapacityRatio":
            return self._shape_score(100.0 * requested / allocatable)
        # LeastAllocated (default)
        return MAX_NODE_SCORE * (allocatable - requested) / allocatable

    def _shape_score(self, utilization: float) -> float:
        """Piecewise-linear over shape points; reference scores are 0..10
        scaled to 0..100 (requested_to_capacity_ratio maxUtilization handling)."""
        pts = self.shape
        if utilization <= pts[0]["utilization"]:
            raw = pts[0]["score"]
        elif utilization >= pts[-1]["utilization"]:
            raw = pts[-1]["score"]
        else:
            raw = pts[-1]["score"]
            for i in range(1, len(pts)):
                if utilization <= pts[i]["utilization"]:
                    u0, s0 = pts[i - 1]["utilization"], pts[i - 1]["score"]
                    u1, s1 = pts[i]["utilization"], pts[i]["score"]
                    raw = s0 + (s1 - s0) * (utilization - u0) / (u1 - u0)
                    break
        return raw * MAX_NODE_SCORE / 10.0


def insufficient_resources(
    pod: PodInfo, node: NodeInfo, ignored: set[str] = frozenset()
) -> list[str]:
    """fitsRequest: list of human-readable insufficiency reasons (empty = fits)."""
    reasons: list[str] = []
    if node.requested.pods + 1 > node.allocatable.pods:
        reasons.append("Too many pods")
    if not pod.requests:
        return reasons
    for rname, req in pod.requests.items():
        if req == 0 or rname in ignored:
            continue
        free = node.allocatable.get(rname) - node.requested.get(rname)
        if req > free:
            reasons.append(f"Insufficient {rname}")
    return reasons


class BalancedAllocation(Plugin):
    """NodeResourcesBalancedAllocation: prefer nodes whose per-resource
    utilization fractions are close to each other (penalize cpu-90%/mem-10%)."""

    NAME = "NodeResourcesBalancedAllocation"
    EXTENSION_POINTS = ("PreScore", "Score")

    def __init__(self, args=None):
        super().__init__(args)
        self.resources = [
            r["name"] if isinstance(r, dict) else r
            for r in self.args.get("resources") or [CPU, MEMORY]
        ]

    def pre_score(self, state: CycleState, pod: PodInfo, nodes) -> Status:
        if not pod.nonzero_requests:
            return Status.skip()
        return Status.success()

    def score(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> float:
        fractions = []
        for rname in self.resources:
            alloc = node.allocatable.get(rname)
            if alloc <= 0:
                continue
            req = node.nonzero_requested.get(rname) + pod.nonzero_requests.get(rname, 0)
            fractions.append(min(req / alloc, 1.0))
        if len(fractions) < 2:
            return 0.0
        mean = sum(fractions) / len(fractions)
        var = sum((f - mean) ** 2 for f in fractions) / len(fractions)
        return (1.0 - math.sqrt(var)) * MAX_NODE_SCORE
