"""DynamicResources: DRA (dynamic resource allocation) scheduling.

Parity target: `pkg/scheduler/framework/plugins/dynamicresources/` over the
resource.k8s.io structured-parameters model (SURVEY §2.3 plugin table,
§2.5 devicemanager). The modern device path: pods reference ResourceClaims;
DRA drivers publish per-node device inventories as ResourceSlices;
DeviceClasses select devices by attribute; the SCHEDULER performs the
allocation (structured parameters) and persists it to claim.status at
PreBind.

Extension points (reference order):
- PreEnqueue: pods whose claims don't exist yet are gated out of the
  active queue (the resourceclaim controller stamps template claims).
- PreFilter: resolve the pod's claim refs → per-claim device requests;
  a claim already allocated to node X restricts candidates to X.
- Filter: every claim must be satisfiable from the node's FREE devices —
  slice inventory minus devices demanded by claims of pods already on the
  node (counted per claim, so shared claims aren't double-charged) —
  honoring matchAttribute constraints (all devices of a claim agree on
  the attribute: single-NUMA alignment the DRA way).
- Reserve/Unreserve: pick concrete devices deterministically and hold
  them in the in-memory assume ledger (mirrors the claim assume cache).
- PreBind: guaranteed-update claim.status with the allocation + the pod
  in reservedFor (the durable record a kubelet/driver would consume).

Deallocation: the resourceclaim controller (controllers/resourceclaim.py)
drops reservedFor entries when consumer pods terminate and deletes
generated claims; freeing is then visible through the claims informer.

TPU-first: the batched backend vectorizes Filter over all nodes from a
dense per-(class, attribute-group) free-count tensor (ops/backend.py
`_dra_state` / `_dra_filter_row`), with in-batch drift handled by the
stateful re-verify — same shape as NodeResourceTopologyMatch.
"""

from __future__ import annotations

import logging

from kubernetes_tpu.api.meta import namespaced_name
from kubernetes_tpu.scheduler.framework import CycleState, Plugin, Status
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot
from kubernetes_tpu.store.mvcc import StoreError

logger = logging.getLogger(__name__)

_STATE_KEY = "DynamicResources/claims"


def pod_claim_keys(pi: PodInfo) -> list[str]:
    """Store keys of the pod's referenced claims (template refs resolve to
    the generated claim's deterministic name `<pod>-<ref name>`)."""
    keys = []
    for ref in pi.resource_claims:
        name = ref.get("resourceClaimName")
        if not name and ref.get("resourceClaimTemplateName"):
            name = f"{pi.name}-{ref.get('name', '')}"
        if name:
            keys.append(f"{pi.namespace}/{name}")
    return keys


def claim_requests(claim: dict) -> list[dict]:
    return ((claim.get("spec") or {}).get("devices") or {}) \
        .get("requests") or []


def claim_match_attrs(claim: dict) -> list[str]:
    return [c["matchAttribute"]
            for c in (((claim.get("spec") or {}).get("devices") or {})
                      .get("constraints") or [])
            if c.get("matchAttribute")]


def claim_allocated_node(claim: dict) -> str | None:
    alloc = (claim.get("status") or {}).get("allocation")
    if alloc:
        return alloc.get("nodeName") or None
    return None


class _ClaimState:
    """PreFilter output carried through the cycle."""

    __slots__ = ("claims", "pinned_node")

    def __init__(self, claims: list[dict], pinned_node: str | None):
        self.claims = claims            # resolved claim objects
        self.pinned_node = pinned_node  # pre-allocated claims pin the node


class DynamicResources(Plugin):
    NAME = "DynamicResources"
    EXTENSION_POINTS = ("PreEnqueue", "PreFilter", "Filter", "Reserve",
                        "PreBind")
    #: Claim/slice churn must requeue gated + unschedulable pods
    #: (EventsToRegister parity).
    EVENTS = ["Pod/Delete", "ResourceClaim/Add", "ResourceClaim/Update",
              "ResourceClaim/Delete", "ResourceSlice/Add",
              "ResourceSlice/Update", "DeviceClass/Add"]

    def __init__(self, args=None):
        super().__init__(args)
        self.store = None
        self._claim_informer = None
        self._slice_informer = None
        self._class_informer = None
        #: claim key -> device names chosen at Reserve, not yet persisted.
        self._assumed: dict[str, dict] = {}
        #: bumped on every assume-ledger mutation: the backend's tensor
        #: cache keys on it (len() alone misses pop+add churn at equal
        #: size, which would serve stale free counts).
        self.assume_seq = 0
        #: bumped on slice/class churn — backend tensor invalidation.
        self.dra_seq = 0
        #: incremental indexes fed by informer events: scanning the whole
        #: claim/slice tables per Filter/Reserve call is O(N·C) at scale.
        #: node name -> {claim key -> claim} for ALLOCATED claims.
        self._alloc_by_node: dict[str, dict[str, dict]] = {}
        #: claim key -> allocated node (for removal on update/delete).
        self._claim_node: dict[str, str] = {}
        #: node name -> device list from that node's slices.
        self._slices_by_node: dict[str, list[dict]] = {}
        #: slice key -> node name it last contributed to.
        self._slice_node: dict[str, str] = {}

    def set_informers(self, factory) -> None:
        self._claim_informer = factory.informer("resourceclaims")
        self._slice_informer = factory.informer("resourceslices")
        self._class_informer = factory.informer("deviceclasses")

        def bump(*_a):
            self.dra_seq += 1

        def index_claim(obj):
            key = namespaced_name(obj)
            prev = self._claim_node.pop(key, None)
            if prev is not None:
                bucket = self._alloc_by_node.get(prev)
                if bucket is not None:
                    bucket.pop(key, None)
            node = claim_allocated_node(obj)
            if node is not None:
                self._alloc_by_node.setdefault(node, {})[key] = obj
                self._claim_node[key] = node

        def claim_settled(obj):
            # The informer now reflects this claim's allocation (or its
            # deletion): the in-memory assume is no longer needed. Keyed
            # dedupe in free_devices() makes the overlap window safe.
            bump()
            index_claim(obj)
            if claim_allocated_node(obj) is not None:
                if self._assumed.pop(namespaced_name(obj), None) is not None:
                    self.assume_seq += 1

        def claim_gone(obj):
            bump()
            key = namespaced_name(obj)
            prev = self._claim_node.pop(key, None)
            if prev is not None:
                bucket = self._alloc_by_node.get(prev)
                if bucket is not None:
                    bucket.pop(key, None)
            if self._assumed.pop(key, None) is not None:
                self.assume_seq += 1

        def index_slice(obj):
            bump()
            key = namespaced_name(obj)
            prev = self._slice_node.pop(key, None)
            spec = obj.get("spec") or {}
            node = spec.get("nodeName")
            for stale in {prev, node} - {None}:
                self._slices_by_node.pop(stale, None)  # lazy rebuild
            if node:
                self._slice_node[key] = node

        def slice_gone(obj):
            bump()
            key = namespaced_name(obj)
            prev = self._slice_node.pop(key, None)
            if prev is not None:
                self._slices_by_node.pop(prev, None)

        from kubernetes_tpu.client import ResourceEventHandler
        self._slice_informer.add_event_handler(ResourceEventHandler(
            on_add=index_slice,
            on_update=lambda old, new: index_slice(new),
            on_delete=slice_gone))
        self._class_informer.add_event_handler(ResourceEventHandler(
            on_add=bump, on_update=lambda old, new: bump(),
            on_delete=bump))
        self._claim_informer.add_event_handler(ResourceEventHandler(
            on_add=claim_settled,
            on_update=lambda old, new: claim_settled(new),
            on_delete=claim_gone))

    def set_scheduler(self, sched) -> None:
        self.store = sched.store

    # -- inventory ---------------------------------------------------------

    def active_for(self, pi: PodInfo) -> bool:
        return bool(pi.resource_claims)

    def _classes(self) -> dict[str, dict]:
        if self._class_informer is None:
            return {}
        return {c["metadata"]["name"]: c
                for c in self._class_informer.indexer.list()}

    def _class_matches(self, cls: dict, device: dict) -> bool:
        sel = (cls.get("spec") or {}).get("selectors") or {}
        attrs = device.get("attributes") or {}
        return all(attrs.get(k) == v for k, v in sel.items())

    def _rebuild_slice_index(self) -> None:
        by_node: dict[str, list[dict]] = {}
        for rs in self._slice_informer.indexer.list():
            spec = rs.get("spec") or {}
            node = spec.get("nodeName")
            if not node:
                continue
            driver = spec.get("driver", "")
            lst = by_node.setdefault(node, [])
            for d in spec.get("devices") or []:
                lst.append({**d, "driver": driver})
        self._slices_by_node = by_node

    def node_devices(self, node_name: str) -> list[dict]:
        """All devices the slices publish for a node (indexed; slice
        churn invalidates, a miss rebuilds the whole index once)."""
        if self._slice_informer is None:
            return []
        cached = self._slices_by_node.get(node_name)
        if cached is None:
            self._rebuild_slice_index()
            cached = self._slices_by_node.get(node_name)
            if cached is None:
                cached = self._slices_by_node[node_name] = []
        return cached

    def _claims_of_residents(self, node: NodeInfo) -> list[dict]:
        """Claims demanded by pods resident on the node — each claim
        counted ONCE even when shared by several resident pods."""
        if self._claim_informer is None:
            return []
        seen: dict[str, dict] = {}
        for pi in node.pods:
            for key in pod_claim_keys(pi):
                if key in seen:
                    continue
                claim = self._claim_informer.indexer.get(key)
                if claim is not None:
                    seen[key] = claim
        return list(seen.values())

    def free_devices(self, node: NodeInfo,
                     extra_claims: list[dict] = ()) -> list[dict]:
        """Node inventory minus consumed devices, charged from three
        ledgers (deduped by claim key):
        (a) every claim whose status.allocation names this node — the
            authoritative record, independent of pod residency;
        (b) UNALLOCATED claims of resident pods — in-batch placements the
            backend's verify path sees before Reserve/PreBind ran;
        (c) `extra_claims` — in-flight reservations of sibling cycles."""
        devices = self.node_devices(node.name)
        if not devices:
            return devices
        classes = self._classes()
        claims: list[dict] = []
        seen: set[str] = set()

        def add(claim: dict) -> None:
            key = namespaced_name(claim)
            if key not in seen:
                seen.add(key)
                claims.append(claim)

        for claim in (self._alloc_by_node.get(node.name) or {}).values():
            add(claim)
        for claim in self._claims_of_residents(node):
            add(claim)
        for claim in extra_claims:
            add(claim)

        taken: set[str] = set()
        for claim in claims:
            alloc = (claim.get("status") or {}).get("allocation")
            if alloc:
                if alloc.get("nodeName") == node.name:
                    taken.update(alloc.get("devices") or [])
                continue  # allocated elsewhere: charges the other node
            # Unallocated resident demand: charge greedily, mirroring the
            # deterministic pick order in _pick_devices.
            picked = self._pick_devices(
                claim, [d for d in devices if d["name"] not in taken],
                classes)
            if picked is not None:
                taken.update(picked)
        return [d for d in devices if d["name"] not in taken]

    def _pick_devices(self, claim: dict, free: list[dict],
                      classes: dict[str, dict]) -> list[str] | None:
        """Deterministically choose devices satisfying the claim from
        `free`, or None if unsatisfiable. Devices are considered in
        sorted-name order. matchAttribute constraints apply to the WHOLE
        claim (reference MatchAttribute semantics): every chosen device —
        across all of the claim's requests — must agree on the attribute,
        so candidate groups are tried claim-wide (smallest fitting group
        first, then lexicographic — stable across host and backend)."""
        pool = sorted(free, key=lambda d: d.get("name", ""))
        attrs = claim_match_attrs(claim)
        reqs = claim_requests(claim)
        if not attrs:
            return self._pick_from(reqs, pool, classes)
        groups: dict[tuple, list[dict]] = {}
        for d in pool:
            gkey = tuple(str((d.get("attributes") or {}).get(a))
                         for a in attrs)
            groups.setdefault(gkey, []).append(d)
        for _size, _gkey, members in sorted(
                (len(m), gkey, m) for gkey, m in groups.items()):
            picked = self._pick_from(reqs, members, classes)
            if picked is not None:
                return picked
        return None

    def _pick_from(self, reqs: list[dict], pool: list[dict],
                   classes: dict[str, dict]) -> list[str] | None:
        chosen: list[str] = []
        for req in reqs:
            cls = classes.get(req.get("deviceClassName", ""))
            if cls is None:
                return None
            count = int(req.get("count", 1))
            matching = [d for d in pool if d["name"] not in chosen
                        and self._class_matches(cls, d)]
            if len(matching) < count:
                return None
            chosen.extend(d["name"] for d in matching[:count])
        return chosen

    # -- PreEnqueue --------------------------------------------------------

    def pre_enqueue(self, pod: PodInfo) -> Status:
        if not pod.resource_claims or self._claim_informer is None:
            return Status.success()
        for key in pod_claim_keys(pod):
            if self._claim_informer.indexer.get(key) is None:
                return Status.unschedulable(
                    f"waiting for resource claim {key}")
        return Status.success()

    # -- PreFilter ---------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: PodInfo,
                   snapshot: Snapshot) -> Status:
        if not self.active_for(pod):
            return Status.skip()
        if self._claim_informer is None:
            return Status.error("DynamicResources informers not wired")
        claims = []
        pinned = None
        for key in pod_claim_keys(pod):
            claim = self._claim_informer.indexer.get(key)
            if claim is None:
                return Status.unschedulable(
                    f"resource claim {key} not found")
            node = claim_allocated_node(claim)
            if node is not None:
                if pinned is not None and pinned != node:
                    # Two claims hold devices on different nodes: no node
                    # can satisfy both — unresolvable until one
                    # deallocates, NOT a retry loop.
                    return Status.unschedulable(
                        "claims allocated on different nodes")
                pinned = node
            claims.append(claim)
        state.write(_STATE_KEY, _ClaimState(claims, pinned))
        return Status.success()

    # -- Filter ------------------------------------------------------------

    def _claim_state(self, state: CycleState,
                     pod: PodInfo) -> "_ClaimState | None":
        """Cycle state from PreFilter, or resolved on demand — the batched
        backend path reaches Reserve/PreBind with a fresh CycleState (the
        solve replaced the host Filter phase)."""
        cs = state.read(_STATE_KEY)
        if cs is not None or not self.active_for(pod) \
                or self._claim_informer is None:
            return cs
        claims = []
        for key in pod_claim_keys(pod):
            claim = self._claim_informer.indexer.get(key)
            if claim is None:
                return None
            claims.append(claim)
        cs = _ClaimState(claims, None)
        state.write(_STATE_KEY, cs)
        return cs

    def filter(self, state: CycleState, pod: PodInfo,
               node: NodeInfo) -> Status:
        cs: _ClaimState | None = state.read(_STATE_KEY)
        if cs is None:
            return Status.success()
        if cs.pinned_node is not None and node.name != cs.pinned_node:
            return Status.unschedulable(
                "resource claim is allocated on another node")
        classes = self._classes()
        free = self.free_devices(node)
        for claim in cs.claims:
            alloc = (claim.get("status") or {}).get("allocation")
            if alloc and alloc.get("nodeName") == node.name:
                continue  # already holds devices here
            picked = self._pick_devices(claim, free, classes)
            if picked is None:
                return Status.unschedulable(
                    "cannot allocate devices for resource claim")
            names = set(picked)
            free = [d for d in free if d["name"] not in names]
        return Status.success()

    # -- Reserve / Unreserve ----------------------------------------------

    def reserve(self, state: CycleState, pod: PodInfo,
                node_name: str) -> Status:
        cs = self._claim_state(state, pod)
        if cs is None:
            if self.active_for(pod):
                return Status.unschedulable(
                    "resource claims vanished before Reserve")
            return Status.success()
        classes = self._classes()
        # Recompute against live state; in-flight assumes of OTHER pods
        # are in self._assumed and must be excluded from the free pool.
        node = None
        added: list[str] = []
        for claim in cs.claims:
            key = namespaced_name(claim)
            alloc = (claim.get("status") or {}).get("allocation")
            if alloc and alloc.get("nodeName") == node_name:
                continue
            if node is None:
                node = _NodeShim(node_name, self)
            free = [d for d in self.free_devices(
                        node, extra_claims=[
                            a["claim"] for a in self._assumed.values()
                            if a["node"] == node_name])]
            picked = self._pick_devices(claim, free, classes)
            if picked is None:
                # Roll back THIS pod's earlier assumes from this call:
                # run_reserve only unreserves plugins that succeeded, so
                # a leak here would phantom-consume devices forever.
                for k in added:
                    self._assumed.pop(k, None)
                if added:
                    self.assume_seq += 1
                return Status.unschedulable(
                    f"devices for claim {key} were taken during the cycle")
            self._assumed[key] = {
                "node": node_name, "devices": picked, "pod": pod.name,
                "claim": _synthetic_allocated(claim, node_name, picked)}
            added.append(key)
        if added:
            self.assume_seq += 1
        return Status.success()

    def unreserve(self, state: CycleState, pod: PodInfo,
                  node_name: str) -> None:
        cs: _ClaimState | None = state.read(_STATE_KEY)
        if cs is None:
            return
        for claim in cs.claims:
            a = self._assumed.get(namespaced_name(claim))
            if a is not None and a.get("pod") == pod.name:
                self._assumed.pop(namespaced_name(claim), None)
                self.assume_seq += 1

    # -- PreBind: persist allocation + reservedFor -------------------------

    async def pre_bind(self, state: CycleState, pod: PodInfo,
                       node_name: str) -> Status:
        cs = self._claim_state(state, pod)
        if cs is None or self.store is None:
            if self.active_for(pod) and self.store is not None:
                return Status.error(
                    "resource claims vanished before PreBind")
            return Status.success()
        for claim in cs.claims:
            key = namespaced_name(claim)
            assumed = self._assumed.get(key)

            def persist(obj):
                status = obj.setdefault("status", {})
                alloc = status.get("allocation")
                if alloc is None:
                    if assumed is None or assumed.get("pod") != pod.name:
                        # Filter said this claim needs devices here but
                        # Reserve recorded nothing — cycle bug; abort.
                        raise StoreError(
                            f"no assumed allocation for claim {key}")
                    status["allocation"] = {
                        "nodeName": node_name,
                        "devices": list(assumed["devices"])}
                elif alloc.get("nodeName") != node_name:
                    raise StoreError(
                        f"claim {key} got allocated on "
                        f"{alloc.get('nodeName')!r} during binding")
                reserved = status.setdefault("reservedFor", [])
                if not any(r.get("name") == pod.name for r in reserved):
                    reserved.append({"resource": "pods", "name": pod.name,
                                     "uid": pod.uid})
                return obj

            try:
                await self.store.guaranteed_update(
                    "resourceclaims", key, persist, return_copy=False)
            except StoreError as e:
                a = self._assumed.get(key)
                if a is not None and a.get("pod") == pod.name:
                    self._assumed.pop(key, None)
                    self.assume_seq += 1
                return Status.error(f"persisting claim {key}: {e}")
            # Success: the assume stays until the claims informer confirms
            # the allocation (claim_settled) — popping now would open a
            # window where neither ledger charges the devices.
        return Status.success()


class _NodeShim:
    """free_devices() only needs .name and .pods; Reserve runs after the
    cache assumed the pod, so resident demand comes from the informer-fed
    claim objects plus the assume ledger — an empty pod list here."""

    __slots__ = ("name", "pods")

    def __init__(self, name: str, _plugin):
        self.name = name
        self.pods = []


def _synthetic_allocated(claim: dict, node_name: str,
                         devices: list[str]) -> dict:
    """A minimal claim-shaped dict whose allocation charges the assumed
    devices in free_devices() without mutating the informer's object."""
    return {"metadata": dict(claim.get("metadata") or {}),
            "spec": claim.get("spec") or {},
            "status": {"allocation": {"nodeName": node_name,
                                      "devices": list(devices)}}}
