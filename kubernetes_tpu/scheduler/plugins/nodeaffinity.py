"""Node-predicate plugins: NodeAffinity, NodeName, NodeUnschedulable,
TaintToleration, NodePorts.

Parity targets: pkg/scheduler/framework/plugins/{nodeaffinity,nodename,
nodeunschedulable,tainttoleration,nodeports} — Filter semantics documented
per class.
"""

from __future__ import annotations

from kubernetes_tpu.api.labels import Requirement, match_node_selector_terms
from kubernetes_tpu.api.types import (
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    TAINT_PREFER_NO_SCHEDULE,
    find_untolerated_taint,
)
from kubernetes_tpu.scheduler.framework import (
    MAX_NODE_SCORE,
    CycleState,
    Plugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot


class NodeName(Plugin):
    """Filter: spec.nodeName, when set, must equal the node's name
    (nodename/node_name.go `Fits`)."""

    NAME = "NodeName"
    EXTENSION_POINTS = ("Filter",)

    def filter(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> Status:
        if pod.node_name and pod.node_name != node.name:
            return Status.unschedulable(
                "node didn't match the requested node name", resolvable=False)
        return Status.success()


class NodeUnschedulable(Plugin):
    """Filter: node.spec.unschedulable blocks pods unless they tolerate the
    unschedulable taint (nodeunschedulable/node_unschedulable.go)."""

    NAME = "NodeUnschedulable"
    EXTENSION_POINTS = ("Filter",)
    EVENTS = ["Node/Add", "Node/Update"]

    def filter(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> Status:
        if not node.unschedulable:
            return Status.success()
        tolerated = any(
            t.get("key") == "node.kubernetes.io/unschedulable"
            or (t.get("operator") == "Exists" and not t.get("key"))
            for t in pod.tolerations
        )
        if tolerated:
            return Status.success()
        return Status.unschedulable("node(s) were unschedulable", resolvable=False)


class NodeAffinity(Plugin):
    """Filter: nodeSelector AND requiredDuringSchedulingIgnoredDuringExecution.
    Score: preferredDuringScheduling weighted terms.
    (nodeaffinity/node_affinity.go `isSchedulableAfterNodeChange`, `Filter`,
    `Score`; addedAffinity from args for profile-level defaults.)"""

    NAME = "NodeAffinity"
    EXTENSION_POINTS = ("PreFilter", "Filter", "Score")
    EVENTS = ["Node/Add", "Node/Update"]

    def pre_filter(self, state: CycleState, pod: PodInfo, snapshot: Snapshot) -> Status:
        if not pod.node_selector and not (pod.affinity.get("nodeAffinity") or {}):
            return Status.skip()
        return Status.success()

    def filter(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> Status:
        if pod.node_selector:
            for k, v in pod.node_selector.items():
                if node.labels.get(k) != v:
                    return Status.unschedulable(
                        "node(s) didn't match Pod's node affinity/selector",
                        resolvable=False)
        na = pod.affinity.get("nodeAffinity") or {}
        required = na.get("requiredDuringSchedulingIgnoredDuringExecution")
        if required:
            terms = required.get("nodeSelectorTerms") or []
            if not match_node_selector_terms(terms, node.labels, node.name):
                return Status.unschedulable(
                    "node(s) didn't match Pod's node affinity/selector",
                    resolvable=False)
        return Status.success()

    def score(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> float:
        na = pod.affinity.get("nodeAffinity") or {}
        preferred = na.get("preferredDuringSchedulingIgnoredDuringExecution") or []
        if not preferred:
            return 0.0
        total = 0
        got = 0
        for term in preferred:
            w = term.get("weight", 1)
            total += w
            sel = term.get("preference") or {}
            ok = True
            for expr in sel.get("matchExpressions") or []:
                r = Requirement(expr["key"], expr["operator"], expr.get("values") or [])
                if not r.matches(node.labels):
                    ok = False
                    break
            if ok:
                got += w
        return MAX_NODE_SCORE * got / total if total else 0.0


class TaintToleration(Plugin):
    """Filter: NoSchedule/NoExecute taints must be tolerated.
    Score: fewer untolerated PreferNoSchedule taints → higher
    (tainttoleration/taint_toleration.go: normalized (1 - count/max))."""

    NAME = "TaintToleration"
    EXTENSION_POINTS = ("Filter", "PreScore", "Score")
    EVENTS = ["Node/Add", "Node/Update"]

    def filter(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> Status:
        taint = find_untolerated_taint(
            node.taints, pod.tolerations, (TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE))
        if taint is not None:
            return Status.unschedulable(
                f"node(s) had untolerated taint {{{taint.get('key')}: "
                f"{taint.get('value', '')}}}", resolvable=False)
        return Status.success()

    def score(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> float:
        # Raw score = count of untolerated PreferNoSchedule taints (lower is
        # better); normalize flips it.
        count = 0
        for taint in node.taints:
            if taint.get("effect") != TAINT_PREFER_NO_SCHEDULE:
                continue
            from kubernetes_tpu.api.types import toleration_tolerates_taint
            if not any(toleration_tolerates_taint(t, taint) for t in pod.tolerations):
                count += 1
        return float(count)

    def normalize_scores(self, state: CycleState, pod: PodInfo,
                         scores: dict[str, float]) -> None:
        if not scores:
            return
        mx = max(scores.values())
        for k, v in scores.items():
            scores[k] = MAX_NODE_SCORE * (mx - v) / mx if mx > 0 else float(MAX_NODE_SCORE)


class NodePorts(Plugin):
    """Filter: requested hostPorts must be free on the node
    (nodeports/node_ports.go `Fits`: conflict on (ip, protocol, port) with
    0.0.0.0 overlapping any ip)."""

    NAME = "NodePorts"
    EXTENSION_POINTS = ("PreFilter", "Filter")
    EVENTS = ["Pod/Delete"]

    def pre_filter(self, state: CycleState, pod: PodInfo, snapshot: Snapshot) -> Status:
        if not pod.host_ports:
            return Status.skip()
        return Status.success()

    def filter(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> Status:
        for (ip, proto, port) in pod.host_ports:
            for (uip, uproto, uport) in node.used_ports:
                if port != uport or proto != uproto:
                    continue
                if ip == "0.0.0.0" or uip == "0.0.0.0" or ip == uip:
                    return Status.unschedulable(
                        "node(s) didn't have free ports for the requested pod ports")
        return Status.success()
