"""Scheduler snapshot records: PodInfo / NodeInfo / Resource.

Parity target: pkg/scheduler/framework/types.go (`NodeInfo` — Requested,
NonZeroRequested, Allocatable, Pods, PodsWithAffinity, PodsWithRequiredAntiAffinity,
UsedPorts, ImageStates, Generation; `PodInfo` — cached affinity terms;
`Resource` — MilliCPU/Memory/EphemeralStorage/AllowedPodNumber/ScalarResources).

These are the *host-side* compiled records. The TPU path compiles them further
into dense arrays (kubernetes_tpu/ops/tensorize.py); both derive from the same
parse so CPU oracle and TPU backend cannot drift on input interpretation.
"""

from __future__ import annotations

from typing import Any, Mapping

from kubernetes_tpu.api.meta import name_of, namespaced_name, uid_of
from kubernetes_tpu.api.types import (
    CPU,
    EPHEMERAL_STORAGE,
    MEMORY,
    PODS,
    node_allocatable,
    pod_host_ports,
    pod_priority,
    pod_requests,
)

#: Resources tracked as dedicated fields in the reference's Resource struct;
#: everything else is a "scalar resource" (extended resources: GPUs/TPUs,
#: hugepages) — we treat them uniformly in one dict.
DEFAULT_RESOURCES = (CPU, MEMORY)

#: Default max pods when status.allocatable omits "pods" (kubelet default).
DEFAULT_MAX_PODS = 110


def _alloc_pods(alloc: Mapping[str, int]) -> int:
    """Allocatable pod count; an explicit "0" means zero, only absence
    falls back to the default."""
    v = alloc.get(PODS)
    return DEFAULT_MAX_PODS if v is None else v // 1000


class Resource:
    """Aggregate resource vector in milli-units + pod count."""

    __slots__ = ("res", "pods")

    def __init__(self, res: Mapping[str, int] | None = None, pods: int = 0):
        self.res: dict[str, int] = dict(res or {})
        self.pods = pods

    def add(self, other: Mapping[str, int]) -> None:
        for k, v in other.items():
            if k == PODS:
                continue
            self.res[k] = self.res.get(k, 0) + v

    def sub(self, other: Mapping[str, int]) -> None:
        for k, v in other.items():
            if k == PODS:
                continue
            self.res[k] = self.res.get(k, 0) - v

    def get(self, name: str) -> int:
        return self.res.get(name, 0)

    def clone(self) -> "Resource":
        return Resource(self.res, self.pods)

    def __repr__(self) -> str:
        return f"Resource({self.res}, pods={self.pods})"


class PodInfo:
    """Parsed pod with scheduling-relevant fields precomputed
    (framework.PodInfo caches affinity terms for the same reason)."""

    __slots__ = (
        "pod", "key", "uid", "name", "namespace", "labels",
        "requests", "nonzero_requests", "priority",
        "node_name", "scheduler_name",
        "node_selector", "affinity", "tolerations",
        "topology_spread_constraints", "scheduling_gates",
        "host_ports", "pvc_names", "resource_claims",
        "required_affinity_terms", "required_anti_affinity_terms",
        "preferred_affinity_terms", "preferred_anti_affinity_terms",
        "attempts", "last_failure", "unschedulable_plugins", "queued_at",
        "enqueued_at", "dequeued_at", "nominated_node",
    )

    def __init__(self, pod: Mapping):
        self.pod = pod
        self.key = namespaced_name(pod)
        self.uid = uid_of(pod)
        self.name = name_of(pod)
        self.namespace = pod.get("metadata", {}).get("namespace", "")
        self.labels = pod.get("metadata", {}).get("labels") or {}
        self.requests = pod_requests(pod)
        self.nonzero_requests = pod_requests(pod, non_zero=True)
        self.priority = pod_priority(pod)
        spec = pod.get("spec", {})
        self.node_name = spec.get("nodeName", "")
        self.scheduler_name = spec.get("schedulerName", "default-scheduler")
        self.node_selector = spec.get("nodeSelector") or {}
        self.affinity = spec.get("affinity") or {}
        self.tolerations = spec.get("tolerations") or []
        self.topology_spread_constraints = spec.get("topologySpreadConstraints") or []
        self.scheduling_gates = [g.get("name") for g in spec.get("schedulingGates") or []]
        self.host_ports = pod_host_ports(pod)
        self.pvc_names = [
            v["persistentVolumeClaim"]["claimName"]
            for v in spec.get("volumes") or []
            if v.get("persistentVolumeClaim", {}).get("claimName")]
        #: spec.resourceClaims entries (DRA): [{"name", and one of
        #: "resourceClaimName" | "resourceClaimTemplateName"}].
        self.resource_claims = spec.get("resourceClaims") or []
        pod_aff = self.affinity.get("podAffinity") or {}
        pod_anti = self.affinity.get("podAntiAffinity") or {}
        self.required_affinity_terms = list(
            pod_aff.get("requiredDuringSchedulingIgnoredDuringExecution") or [])
        self.required_anti_affinity_terms = list(
            pod_anti.get("requiredDuringSchedulingIgnoredDuringExecution") or [])
        self.preferred_affinity_terms = list(
            pod_aff.get("preferredDuringSchedulingIgnoredDuringExecution") or [])
        self.preferred_anti_affinity_terms = list(
            pod_anti.get("preferredDuringSchedulingIgnoredDuringExecution") or [])
        # Queue bookkeeping (queuedPodInfo in the reference).
        self.attempts = 0
        self.last_failure = ""
        self.unschedulable_plugins: set[str] = set()
        self.queued_at = 0.0
        #: endpoints of the retroactive queue-wait span, same clock as
        #: queued_at: enqueued_at is re-stamped on every activeQ entry
        #: (so a retry's span covers only THIS attempt's wait, not prior
        #: cycles/backoff), dequeued_at when pop_batch hands it out.
        self.enqueued_at = 0.0
        self.dequeued_at = 0.0
        self.nominated_node = ""

    @property
    def has_required_anti_affinity(self) -> bool:
        return bool(self.required_anti_affinity_terms)

    @property
    def has_affinity_constraints(self) -> bool:
        return bool(
            self.required_affinity_terms
            or self.required_anti_affinity_terms
            or self.preferred_affinity_terms
            or self.preferred_anti_affinity_terms
        )

    def __repr__(self) -> str:
        return f"PodInfo({self.key})"


class NodeInfo:
    """Per-node aggregate the Filter/Score plugins read.

    Mirrors framework.NodeInfo: the node object + resident pods + running
    resource sums + used host ports, with a generation for incremental
    snapshotting.
    """

    __slots__ = (
        "node", "name", "labels", "allocatable", "taints", "unschedulable",
        "requested", "nonzero_requested", "pods", "pods_with_affinity",
        "pods_with_required_anti_affinity", "used_ports", "image_names",
        "generation", "spec_epoch",
    )

    def __init__(self, node: Mapping | None = None):
        self.node = node
        self.name = name_of(node) if node else ""
        self.labels: dict[str, str] = (
            node.get("metadata", {}).get("labels") or {} if node else {}
        )
        alloc = node_allocatable(node) if node else {}
        self.allocatable = Resource(
            {k: v for k, v in alloc.items() if k != PODS},
            pods=_alloc_pods(alloc),
        )
        self.taints = list(node.get("spec", {}).get("taints") or []) if node else []
        self.unschedulable = bool(node.get("spec", {}).get("unschedulable")) if node else False
        self.requested = Resource()
        self.nonzero_requested = Resource()
        self.pods: list[PodInfo] = []
        self.pods_with_affinity: list[PodInfo] = []
        self.pods_with_required_anti_affinity: list[PodInfo] = []
        self.used_ports: set[tuple[str, str, int]] = set()
        self.image_names: set[str] = set()
        if node:
            for img in node.get("status", {}).get("images") or []:
                for tag in img.get("names") or []:
                    self.image_names.add(tag)
        self.generation = 0
        # Monotonic count of node-object (spec/labels/taints) changes —
        # unlike `generation` it does NOT move on pod add/remove, so
        # consumers keyed on static node state (the TPU backend's taint
        # interning and signature-cached rows) can reuse work across
        # pod-churn cycles without the id()-recycling hazard.
        self.spec_epoch = 1 if node else 0

    def set_node(self, node: Mapping) -> None:
        self.node = node
        self.name = name_of(node)
        self.labels = node.get("metadata", {}).get("labels") or {}
        alloc = node_allocatable(node)
        self.allocatable = Resource(
            {k: v for k, v in alloc.items() if k != PODS},
            pods=_alloc_pods(alloc),
        )
        self.taints = list(node.get("spec", {}).get("taints") or [])
        self.unschedulable = bool(node.get("spec", {}).get("unschedulable"))
        self.image_names = set()
        for img in node.get("status", {}).get("images") or []:
            for tag in img.get("names") or []:
                self.image_names.add(tag)
        self.spec_epoch += 1

    def add_pod(self, pi: PodInfo) -> None:
        self.pods.append(pi)
        self.requested.add(pi.requests)
        self.nonzero_requested.add(pi.nonzero_requests)
        self.requested.pods += 1
        if pi.has_affinity_constraints:
            self.pods_with_affinity.append(pi)
        if pi.has_required_anti_affinity:
            self.pods_with_required_anti_affinity.append(pi)
        self.used_ports.update(pi.host_ports)

    def remove_pod(self, pod_key: str) -> bool:
        for lst in (self.pods, self.pods_with_affinity,
                    self.pods_with_required_anti_affinity):
            for i, pi in enumerate(lst):
                if pi.key == pod_key:
                    if lst is self.pods:
                        self.requested.sub(pi.requests)
                        self.nonzero_requested.sub(pi.nonzero_requests)
                        self.requested.pods -= 1
                        self.used_ports.difference_update(pi.host_ports)
                    del lst[i]
                    break
        return True

    def clone(self) -> "NodeInfo":
        ni = NodeInfo.__new__(NodeInfo)
        ni.node = self.node
        ni.name = self.name
        ni.labels = self.labels
        ni.allocatable = self.allocatable.clone()
        ni.taints = self.taints
        ni.unschedulable = self.unschedulable
        ni.requested = self.requested.clone()
        ni.nonzero_requested = self.nonzero_requested.clone()
        ni.pods = list(self.pods)
        ni.pods_with_affinity = list(self.pods_with_affinity)
        ni.pods_with_required_anti_affinity = list(self.pods_with_required_anti_affinity)
        ni.used_ports = set(self.used_ports)
        ni.image_names = set(self.image_names)
        ni.generation = self.generation
        ni.spec_epoch = self.spec_epoch
        return ni

    def __repr__(self) -> str:
        return f"NodeInfo({self.name}, pods={len(self.pods)})"


class Snapshot:
    """Immutable-by-convention view handed to a scheduling cycle
    (internal/cache/snapshot.go `Snapshot`)."""

    def __init__(self, nodes: list[NodeInfo] | None = None, generation: int = 0,
                 *, by_name: dict | None = None,
                 have_affinity: list | None = None,
                 have_anti_affinity: list | None = None):
        self.nodes = nodes or []
        self.generation = generation
        # The incremental cache passes its maintained structures (already
        # consistent with `nodes`) so snapshot construction is O(changed),
        # not three O(N) scans per cycle — the 200k-preset host-prep fix.
        self._by_name = by_name if by_name is not None \
            else {n.name: n for n in self.nodes}
        self.have_pods_with_affinity = have_affinity \
            if have_affinity is not None \
            else [n for n in self.nodes if n.pods_with_affinity]
        self.have_pods_with_required_anti_affinity = have_anti_affinity \
            if have_anti_affinity is not None else [
                n for n in self.nodes if n.pods_with_required_anti_affinity]
        #: Incremental host-prep handles (set by SchedulerCache; the
        #: defaults mean "unknown — do the full walk"): `set_epoch`
        #: changes when the node SET/order changes, `spec_seq` when any
        #: node object's spec changed, and `changed_since(gen)` returns
        #: the snapshot-order indices of nodes whose generation advanced
        #: past `gen` (None = outside the retained window).
        self.set_epoch = -1
        self.spec_seq = -1
        self.changed_since = None

    def get(self, name: str) -> NodeInfo | None:
        return self._by_name.get(name)

    def __len__(self) -> int:
        return len(self.nodes)

    def __iter__(self):
        return iter(self.nodes)
