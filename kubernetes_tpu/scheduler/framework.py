"""The scheduling framework: extension points, Status codes, CycleState.

Parity target: pkg/scheduler/framework/interface.go (`Plugin`,
`PreEnqueuePlugin`, `QueueSortPlugin`, `PreFilterPlugin`, `FilterPlugin`,
`PostFilterPlugin`, `PreScorePlugin`, `ScorePlugin` + `ScoreExtensions`,
`ReservePlugin`, `PermitPlugin`, `PreBindPlugin`, `BindPlugin`,
`PostBindPlugin`; `Status`/`Code`) and framework/runtime/framework.go
(`frameworkImpl.RunFilterPlugins` / `RunScorePlugins` / ... with per-plugin
duration metrics).

The state machine per scheduling attempt (schedule_one.go):

    PreEnqueue -> [queue] -> PreFilter -> Filter -> (PostFilter on failure)
      -> PreScore -> Score -> NormalizeScore -> Reserve -> Permit
      -> [async] WaitOnPermit -> PreBind -> Bind -> PostBind

TPU-first deviation: plugins additionally may expose **batch kernels**
(`filter_batch` / `score_batch`) that compute a whole (P pods × N nodes) mask
or score tensor at once; the TPU backend (ops/solver.py) composes those instead
of the per-(pod,node) methods. A plugin without a batch kernel falls back to
the host path for that extension point — the per-extension-point backend
selection the north star's feature gate demands.
"""

from __future__ import annotations

import asyncio
import contextlib
import time
from typing import Any, Callable, Iterable, Mapping

from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot

#: shared no-op context manager (stateless, safe to re-enter): the
#: disabled-tracer fast path of ep_span costs one attribute check + this.
_NULL_CM = contextlib.nullcontext()

# --- Status codes (framework.Code) -----------------------------------------

SUCCESS = 0
ERROR = 1
UNSCHEDULABLE = 2
UNSCHEDULABLE_AND_UNRESOLVABLE = 3  # preemption won't help
WAIT = 4   # Permit parked the pod (gang scheduling)
SKIP = 5

MAX_NODE_SCORE = 100
MIN_NODE_SCORE = 0


class Status:
    __slots__ = ("code", "reasons", "plugin")

    def __init__(self, code: int = SUCCESS, reasons: Iterable[str] = (), plugin: str = ""):
        self.code = code
        self.reasons = list(reasons)
        self.plugin = plugin

    @classmethod
    def success(cls) -> "Status":
        return cls(SUCCESS)

    @classmethod
    def unschedulable(cls, *reasons: str, resolvable: bool = True) -> "Status":
        return cls(UNSCHEDULABLE if resolvable else UNSCHEDULABLE_AND_UNRESOLVABLE, reasons)

    @classmethod
    def error(cls, *reasons: str) -> "Status":
        return cls(ERROR, reasons)

    @classmethod
    def skip(cls) -> "Status":
        return cls(SKIP)

    @classmethod
    def wait(cls) -> "Status":
        return cls(WAIT)

    def is_success(self) -> bool:
        return self.code == SUCCESS

    def is_skip(self) -> bool:
        return self.code == SKIP

    def is_wait(self) -> bool:
        return self.code == WAIT

    def is_unschedulable(self) -> bool:
        return self.code in (UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE)

    def message(self) -> str:
        return "; ".join(self.reasons)

    def with_plugin(self, name: str) -> "Status":
        self.plugin = self.plugin or name
        return self

    def __repr__(self) -> str:
        names = {0: "Success", 1: "Error", 2: "Unschedulable",
                 3: "UnschedulableAndUnresolvable", 4: "Wait", 5: "Skip"}
        return f"Status({names[self.code]}, {self.reasons!r}, plugin={self.plugin!r})"


class CycleState:
    """Per-attempt scratch space (framework/cycle_state.go): plugins stash
    PreFilter/PreScore precomputation under their own keys."""

    def __init__(self):
        self._data: dict[str, Any] = {}
        self.skip_filter_plugins: set[str] = set()
        self.skip_score_plugins: set[str] = set()

    def write(self, key: str, value: Any) -> None:
        self._data[key] = value

    def read(self, key: str) -> Any:
        return self._data.get(key)

    def clone(self) -> "CycleState":
        cs = CycleState()
        cs._data = dict(self._data)
        cs.skip_filter_plugins = set(self.skip_filter_plugins)
        cs.skip_score_plugins = set(self.skip_score_plugins)
        return cs


# --- Plugin base -----------------------------------------------------------

class Plugin:
    """Base plugin. Subclasses override the extension points they implement
    and declare them in EXTENSION_POINTS. Args come from the per-plugin
    config (KubeSchedulerConfiguration pluginConfig)."""

    NAME = "Plugin"
    EXTENSION_POINTS: tuple[str, ...] = ()

    def __init__(self, args: Mapping | None = None):
        self.args = dict(args or {})

    # PreEnqueue: gate pods out of the active queue entirely.
    def pre_enqueue(self, pod: PodInfo) -> Status:
        return Status.success()

    # QueueSort: less(a, b) ordering for the active queue.
    def less(self, a: PodInfo, b: PodInfo) -> bool:
        raise NotImplementedError

    # PreFilter: per-pod precompute; may narrow candidate nodes or Skip.
    def pre_filter(self, state: CycleState, pod: PodInfo,
                   snapshot: Snapshot) -> Status:
        return Status.success()

    # Filter: feasibility of pod on one node.
    def filter(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> Status:
        return Status.success()

    # PostFilter: runs when no node passed Filter (preemption lives here).
    def post_filter(self, state: CycleState, pod: PodInfo, snapshot: Snapshot,
                    filtered_status: Mapping[str, Status]) -> tuple[str, Status]:
        return "", Status.unschedulable()

    # PreScore
    def pre_score(self, state: CycleState, pod: PodInfo,
                  nodes: list[NodeInfo]) -> Status:
        return Status.success()

    # Score: 0..100 per node.
    def score(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> float:
        return 0.0

    # NormalizeScore (ScoreExtensions): rescale this plugin's raw scores.
    def normalize_scores(self, state: CycleState, pod: PodInfo,
                         scores: dict[str, float]) -> None:
        return None

    # Reserve / Unreserve
    def reserve(self, state: CycleState, pod: PodInfo, node_name: str) -> Status:
        return Status.success()

    def unreserve(self, state: CycleState, pod: PodInfo, node_name: str) -> None:
        return None

    # Permit: may return Wait (gang scheduling parks here) with a timeout.
    def permit(self, state: CycleState, pod: PodInfo,
               node_name: str) -> tuple[Status, float]:
        return Status.success(), 0.0

    # PreBind / Bind / PostBind
    async def pre_bind(self, state: CycleState, pod: PodInfo, node_name: str) -> Status:
        return Status.success()

    async def bind(self, state: CycleState, pod: PodInfo, node_name: str) -> Status:
        return Status.skip()

    def post_bind(self, state: CycleState, pod: PodInfo, node_name: str) -> None:
        return None

    # --- batch kernels (TPU path) -----------------------------------------
    # Implemented by tensorizable plugins; see ops/plugins_tpu.py. Returning
    # NotImplemented routes this plugin through the host path.

    def filter_batch(self, tensors, pods):  # -> (P,N) bool mask or NotImplemented
        return NotImplemented

    def score_batch(self, tensors, pods):  # -> (P,N) float scores or NotImplemented
        return NotImplemented


class EnqueueExtensions:
    """Which cluster events may make a pod schedulable again
    (framework.EnqueueExtensions.EventsToRegister → QueueingHint).
    Event strings: "Node/Add", "Node/Update", "Pod/Delete", "Pod/Add", ..."""

    @staticmethod
    def events_for(plugin: Plugin) -> list[str]:
        return getattr(plugin, "EVENTS", ["Node/Add", "Node/Update", "Pod/Delete"])


# --- Framework runner ------------------------------------------------------

class Framework:
    """frameworkImpl: a configured set of plugins per profile, with
    per-plugin/per-extension-point timing recorded for metrics parity."""

    def __init__(
        self,
        plugins: list[Plugin],
        score_weights: Mapping[str, int] | None = None,
        profile_name: str = "default-scheduler",
        metrics=None,
        disabled: Mapping[str, Iterable[str]] | None = None,
    ):
        self.profile_name = profile_name
        self.plugins = plugins
        self.score_weights = dict(score_weights or {})
        self.metrics = metrics
        #: utils/tracing.Tracer injected by the Scheduler (like metrics):
        #: each extension-point run_* becomes a child span of the attempt
        #: when tracing is on; a None/disabled tracer costs one check.
        self.tracer = None
        disabled = {k: set(v) for k, v in (disabled or {}).items()}

        def enabled(point: str) -> list[Plugin]:
            off = disabled.get(point, set()) | disabled.get("*", set())
            return [p for p in plugins
                    if point in p.EXTENSION_POINTS and p.NAME not in off]

        self.pre_enqueue_plugins = enabled("PreEnqueue")
        self.queue_sort_plugins = enabled("QueueSort")
        self.pre_filter_plugins = enabled("PreFilter")
        self.filter_plugins = enabled("Filter")
        self.post_filter_plugins = enabled("PostFilter")
        self.pre_score_plugins = enabled("PreScore")
        self.score_plugins = enabled("Score")
        self.reserve_plugins = enabled("Reserve")
        self.permit_plugins = enabled("Permit")
        self.pre_bind_plugins = enabled("PreBind")
        self.bind_plugins = enabled("Bind")
        self.post_bind_plugins = enabled("PostBind")

    def ep_span(self, point: str):
        """Context manager for one extension point's span (a no-op unless
        the injected tracer is enabled) — the utiltrace step analog at
        span granularity; per-plugin timing stays on the metrics path."""
        t = self.tracer
        if t is not None and t.enabled:
            return t.span(f"framework.{point}", profile=self.profile_name)
        return _NULL_CM

    def _timed(self, plugin: Plugin, point: str, fn: Callable, *args):
        t0 = time.perf_counter()
        try:
            return fn(*args)
        finally:
            if self.metrics is not None:
                self.metrics.observe_plugin(plugin.NAME, point,
                                            time.perf_counter() - t0)

    # -- queue hooks --

    def run_pre_enqueue(self, pod: PodInfo) -> Status:
        for p in self.pre_enqueue_plugins:
            st = self._timed(p, "PreEnqueue", p.pre_enqueue, pod)
            if not st.is_success():
                return st.with_plugin(p.NAME)
        return Status.success()

    def less(self, a: PodInfo, b: PodInfo) -> bool:
        for p in self.queue_sort_plugins:
            return p.less(a, b)
        return a.queued_at < b.queued_at

    # -- scheduling cycle --

    def run_pre_filter(self, state: CycleState, pod: PodInfo,
                       snapshot: Snapshot) -> Status:
        with self.ep_span("PreFilter"):
            for p in self.pre_filter_plugins:
                st = self._timed(p, "PreFilter", p.pre_filter, state, pod,
                                 snapshot)
                if st.is_skip():
                    state.skip_filter_plugins.add(p.NAME)
                    continue
                if not st.is_success():
                    return st.with_plugin(p.NAME)
            return Status.success()

    def run_filters(self, state: CycleState, pod: PodInfo,
                    node: NodeInfo) -> Status:
        for p in self.filter_plugins:
            if p.NAME in state.skip_filter_plugins:
                continue
            st = self._timed(p, "Filter", p.filter, state, pod, node)
            if not st.is_success():
                return st.with_plugin(p.NAME)
        return Status.success()

    def run_post_filters(self, state: CycleState, pod: PodInfo,
                         snapshot: Snapshot,
                         statuses: Mapping[str, Status]) -> tuple[str, Status]:
        with self.ep_span("PostFilter"):
            for p in self.post_filter_plugins:
                nominated, st = self._timed(
                    p, "PostFilter", p.post_filter, state, pod, snapshot,
                    statuses)
                if st.is_success() or not st.is_unschedulable():
                    return nominated, st.with_plugin(p.NAME)
            return "", Status.unschedulable()

    def run_pre_score(self, state: CycleState, pod: PodInfo,
                      nodes: list[NodeInfo]) -> Status:
        with self.ep_span("PreScore"):
            for p in self.pre_score_plugins:
                st = self._timed(p, "PreScore", p.pre_score, state, pod, nodes)
                if st.is_skip():
                    state.skip_score_plugins.add(p.NAME)
                    continue
                if not st.is_success():
                    return st.with_plugin(p.NAME)
            return Status.success()

    def run_scores(self, state: CycleState, pod: PodInfo,
                   nodes: list[NodeInfo]) -> dict[str, float]:
        """Weighted sum over score plugins (RunScorePlugins + NormalizeScore +
        plugin weight application)."""
        with self.ep_span("Score"):
            totals = {n.name: 0.0 for n in nodes}
            for p in self.score_plugins:
                if p.NAME in state.skip_score_plugins:
                    continue
                raw = {}
                for n in nodes:
                    raw[n.name] = self._timed(p, "Score", p.score, state,
                                              pod, n)
                self._timed(p, "NormalizeScore", p.normalize_scores, state,
                            pod, raw)
                w = self.score_weights.get(p.NAME, 1)
                for name, s in raw.items():
                    totals[name] += w * s
            return totals

    # -- reserve / permit / bind --

    def run_reserve(self, state: CycleState, pod: PodInfo, node_name: str) -> Status:
        with self.ep_span("Reserve"):
            done: list[Plugin] = []
            for p in self.reserve_plugins:
                st = self._timed(p, "Reserve", p.reserve, state, pod,
                                 node_name)
                if not st.is_success():
                    for q in done:
                        q.unreserve(state, pod, node_name)
                    return st.with_plugin(p.NAME)
                done.append(p)
            return Status.success()

    def run_unreserve(self, state: CycleState, pod: PodInfo, node_name: str) -> None:
        for p in reversed(self.reserve_plugins):
            self._timed(p, "Unreserve", p.unreserve, state, pod, node_name)

    def run_permit(self, state: CycleState, pod: PodInfo,
                   node_name: str) -> tuple[Status, float]:
        with self.ep_span("Permit"):
            max_timeout = 0.0
            waiting = False
            for p in self.permit_plugins:
                st, timeout = self._timed(p, "Permit", p.permit, state, pod,
                                          node_name)
                if st.is_wait():
                    waiting = True
                    max_timeout = max(max_timeout, timeout)
                elif not st.is_success():
                    return st.with_plugin(p.NAME), 0.0
            return (Status.wait(), max_timeout) if waiting \
                else (Status.success(), 0.0)

    async def run_pre_bind(self, state: CycleState, pod: PodInfo,
                           node_name: str) -> Status:
        with self.ep_span("PreBind"):
            for p in self.pre_bind_plugins:
                t0 = time.perf_counter()
                st = await p.pre_bind(state, pod, node_name)
                if self.metrics is not None:
                    self.metrics.observe_plugin(p.NAME, "PreBind",
                                                time.perf_counter() - t0)
                if not st.is_success():
                    return st.with_plugin(p.NAME)
            return Status.success()

    async def run_bind(self, state: CycleState, pod: PodInfo,
                       node_name: str) -> Status:
        with self.ep_span("Bind"):
            for p in self.bind_plugins:
                t0 = time.perf_counter()
                st = await p.bind(state, pod, node_name)
                if self.metrics is not None:
                    self.metrics.observe_plugin(p.NAME, "Bind",
                                                time.perf_counter() - t0)
                if st.is_skip():
                    continue
                return st.with_plugin(p.NAME)
            return Status.error("no bind plugin handled the pod")

    def run_post_bind(self, state: CycleState, pod: PodInfo, node_name: str) -> None:
        with self.ep_span("PostBind"):
            for p in self.post_bind_plugins:
                self._timed(p, "PostBind", p.post_bind, state, pod, node_name)
