"""Central registry of every `KTPU_*` environment flag.

Before this module, 30+ call sites read `os.environ` directly with
ad-hoc parsing: three different boolean spellings, two different
defaults for the SAME flag (`KTPU_TRACE_THRESHOLD_MS` defaulted to
"disabled" in the tracer and to 100 ms in the scheduler), import-time
reads that silently ignored env changes made after import (the bench
had to set overrides before importing the backend), and `float(env)` /
`int(env)` calls that crashed the process on a malformed value.

The registry is the single source of truth: name, default, parser,
one-line doc, and whether the flag is a structural kill switch. Every
read in the tree goes through `get()` — a LIVE `os.environ` read per
call, so tests and the bench can flip knobs between runs — and the
static-analysis flag pass (`kubernetes_tpu/analysis/flags_pass.py`)
fails the build on any `KTPU_*` environ read that bypasses it, on
registry entries without docs or tests, and on a README flag table
that drifted from `render_markdown_table()`.

Parsing is deliberately forgiving: a malformed value degrades to the
flag's default (a typo in an env var must never crash a control
plane), and booleans accept the union of the spellings that grew up in
the tree ("0"/"false"/"off"/"no", any case, disable).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable

__all__ = ["Flag", "FLAGS", "get", "get_raw", "scoped_set",
           "render_markdown_table"]

#: spellings that read as "off" for boolean flags (case-insensitive);
#: everything else non-empty reads as "on".
_FALSE = frozenset(("0", "false", "off", "no"))


def _parse_bool(raw: str) -> bool:
    return raw.strip().lower() not in _FALSE


def _parse_int(raw: str) -> int:
    return int(raw.strip())


def _parse_float(raw: str) -> float:
    return float(raw.strip())


def _parse_ms(raw: str) -> float:
    return max(0.0, float(raw.strip()))


def _parse_str(raw: str) -> str:
    return raw


def _parse_solve_mode(raw: str) -> str:
    v = raw.strip().lower()
    if v not in ("greedy", "optimal", "auto"):
        raise ValueError(raw)  # degrades to the default, per read()
    return v


def _parse_wal_fsync(raw: str) -> str:
    v = raw.strip().lower()
    if v not in ("batch", "always"):
        raise ValueError(raw)  # degrades to the default, per read()
    return v


def _parse_pallas(raw: str) -> str:
    v = raw.strip().lower()
    if v in ("auto", "on", "off", "interpret"):
        return v
    if v in _FALSE:          # the boolean spellings keep working
        return "off"
    if v in ("1", "true", "yes"):
        return "on"
    raise ValueError(raw)    # degrades to the default, per read()


@dataclass(frozen=True)
class Flag:
    name: str
    default: Any
    parse: Callable[[str], Any] = field(repr=False)
    doc: str
    #: structural kill switch: flipping it degrades a subsystem to its
    #: pre-feature shape (the differential-test contract), rather than
    #: tuning a knob.
    kill_switch: bool = False

    def read(self) -> Any:
        raw = os.environ.get(self.name)
        if raw is None or raw == "":
            return self.default
        try:
            return self.parse(raw)
        except (ValueError, TypeError):
            return self.default


def _flag(name, default, parse, doc, kill_switch=False) -> Flag:
    return Flag(name=name, default=default, parse=parse, doc=doc,
                kill_switch=kill_switch)


#: The registry. Order is the README table order: kill switches first,
#: then tuning overrides, then debug/test knobs.
FLAGS: dict[str, Flag] = {f.name: f for f in (
    _flag("KTPU_SERVING", True, _parse_bool,
          "Online serving tier (admission window + resident planes + "
          "single-pod fast path). `0` degrades the dispatch loop "
          "structurally to the pre-serving shape.", kill_switch=True),
    _flag("KTPU_CLASS_PLANES", True, _parse_bool,
          "Class-dictionary (C,N) device planes. `0` falls back to "
          "per-pod planes (C == P identity), bit-identical assignments.",
          kill_switch=True),
    _flag("KTPU_WAVEFRONT", True, _parse_bool,
          "Speculative wavefront solve (W pods per scan step with exact "
          "conflict replay). `0` degrades structurally to the "
          "one-pod-per-step W=1 scans, bit-identical assignments.",
          kill_switch=True),
    _flag("KTPU_PALLAS", "auto", _parse_pallas,
          "Fused Pallas wavefront solve kernel (ops/pallas_kernel.py). "
          "`off` is the kill switch — the exact r20 lax.scan call graph, "
          "bit-identical assignments. `auto` (default) compiles the "
          "kernel on accelerator backends only and keeps the scan on "
          "CPU; `on` forces the kernel (compiled when lowering is "
          "available, else interpret); `interpret` forces the "
          "interpreter everywhere (the CPU tier-1 validation mode). "
          "Structural fallbacks to the scan are counted in "
          "`solver_pallas_fallbacks_total`.", kill_switch=True),
    _flag("KTPU_BLOCK_INDEX", True, _parse_bool,
          "Two-level block-sparse node index for the shortlist "
          "prefilter: per-block aggregate planes + an O(C·B) bound scan "
          "gate which node columns the chunk-start score pass touches, "
          "exactly (a block whose score upper bound loses to the "
          "(K+1)-th shortlist value cannot hold a top-K column). `0` "
          "degrades structurally to the full-width r18/r21 prefilter "
          "call graph, bit-identical assignments.", kill_switch=True),
    _flag("KTPU_WAVE_WIDTH", None, _parse_int,
          "Wavefront width override (pods evaluated per scan step). "
          "Unset = the AdaptiveTuner policy row picks W and shrinks it "
          "when the measured replay fraction climbs."),
    _flag("KTPU_SOLVE_MODE", "auto", _parse_solve_mode,
          "Batch solve mode: `greedy` pins the r18 wavefront scan call "
          "graph (bit-identical assignments — the kill switch), "
          "`optimal` forces the device-side Sinkhorn transport plan + "
          "feasible rounding for every eligible chunk, `auto` routes "
          "drain-scale and gang chunks to optimal per the tuner policy "
          "row (serving single-pod traffic never routes here; above "
          "the structural large-N row non-gang chunks keep the greedy "
          "scan — the plan's fixed dense (C,N) iteration cost is the "
          "linear-in-N wall the block index removes).",
          kill_switch=True),
    _flag("KTPU_SINKHORN_ITERS", 24, _parse_int,
          "Sinkhorn iterations per optimal-mode chunk (the temperature "
          "annealing's 3 stages split this count)."),
    _flag("KTPU_SINKHORN_TEMP", 0.05, _parse_float,
          "Final Sinkhorn temperature (entropic regularization weight "
          "on the row-normalized cost) — annealing runs 4x -> 2x -> 1x "
          "this value; lower = sharper, closer-to-argmax plans."),
    _flag("KTPU_DESCHEDULER", False, _parse_bool,
          "Default-enable the rebalance descheduler "
          "(controllers/descheduler.py) in ChurnDay scenarios that "
          "don't pin it: periodic evict-and-replace consolidation "
          "moves scored from the resident device planes."),
    _flag("KTPU_DESCHEDULER_BUDGET", 8, _parse_int,
          "Disruption budget: max evict-and-replace moves the "
          "descheduler may issue per sync cycle."),
    _flag("KTPU_TOPOLOGY", True, _parse_bool,
          "Topology-aware TPU-slice placement (kubernetes_tpu/topology): "
          "interconnect coordinate planes on the cluster tensors, the "
          "device-side contiguous sub-mesh Filter/Score behind the "
          "TopologySlice plugin, and Coscheduling's sliceShape contiguity "
          "check at Permit. `0` degrades structurally to flat capacity "
          "vectors — count-only gangs, no coordinate planes, assignments "
          "bit-identical on topology-free workloads.", kill_switch=True),
    _flag("KTPU_MESH_SHAPE", "auto", _parse_str,
          "Interconnect mesh dimensions, e.g. `4x8` (2D torus), `2x4x4` "
          "(3D torus) or `4x8:mesh` (no wraparound). `auto` derives a "
          "near-square 2D torus from the node count. Nodes map to "
          "coordinates via the `ktpu.io/topology-coord` label agents "
          "stamp at registration, falling back to the trailing integer "
          "in the node name (row-major)."),
    _flag("KTPU_WATCH_CACHE", True, _parse_bool,
          "Watch-cache serving tier (store/cacher.py). `0` degrades "
          "every LIST/watch to the direct-mvcc path.", kill_switch=True),
    _flag("KTPU_POLICY_INDEX", True, _parse_bool,
          "Pre-indexed ValidatingAdmissionPolicy matching (policy/"
          "vap.py): exact (resource, operation) reverse maps + interned "
          "namespace-selector signatures make admission O(matching "
          "policies). `0` degrades structurally to the linear "
          "all-policies scan, bit-identical verdicts.", kill_switch=True),
    _flag("KTPU_SHARDS", None, _parse_int,
          "Control-plane shard count override; `1` is the kill switch "
          "(plain single MVCCStore). Unset = the node-count threshold "
          "policy picks.", kill_switch=True),
    _flag("KTPU_SHARD_THRESHOLD", 100_000, _parse_int,
          "Node count at which the flagless shard policy switches from "
          "1 shard to 8 (store/sharded.control_plane_shards)."),
    _flag("KTPU_PROCESSES", None, _parse_int,
          "Control-plane OS-process count (multiproc/): each store "
          "shard becomes its own apiserver process on a unix-socket "
          "KTPU wire, the scheduler an active/standby process pair. "
          "`1` is the kill switch — the classic in-process tree, "
          "bit-identical call graph. Unset = in-process (the bench's "
          "--processes flag is the spawn path).", kill_switch=True),
    _flag("KTPU_WAL", True, _parse_bool,
          "Write-ahead log between KTPU_DATA_DIR snapshots (store/"
          "durable.py): append every committed mvcc write, replay from "
          "the snapshot RV on recovery. `0` degrades durability to "
          "snapshot-only (the pre-WAL r16 shape).", kill_switch=True),
    _flag("KTPU_WAL_FSYNC", "batch", _parse_wal_fsync,
          "WAL fsync policy: `always` fsyncs per commit (the etcd "
          "posture — an acknowledged write is on disk), `batch` group-"
          "commits on the flush tick (durability window = one flush "
          "interval, fsync off the commit path)."),
    _flag("KTPU_LEASE_DURATION", 15.0, _parse_float,
          "Leader-election lease duration in seconds (client/"
          "leaderelection.py). Renew deadline and retry period scale "
          "with it (2/3 and 2/15 of the lease, the reference's "
          "15/10/2 shape) — shorter lease = faster failover detection "
          "at more lease-write traffic."),
    _flag("KTPU_CLASS_PAD", 31, _parse_int,
          "Max real pod-equivalence classes per chunk before the "
          "per-pod fallback (plane rows bucket to the next power of "
          "two)."),
    _flag("KTPU_PIPELINE_DEPTH", None, _parse_int,
          "Solve-pipeline depth override (chunks in flight ahead of "
          "the fetch). Unset = the AdaptiveTuner picks from measured "
          "transfer latency."),
    _flag("KTPU_SHORTLIST_K", None, _parse_int,
          "Shortlist width override for the pruned solve; `0` disables "
          "pruning. Unset = the tuner derives K from chunk width and "
          "fallback rate."),
    _flag("KTPU_BLOCK_WIDTH", None, _parse_int,
          "Block width override (node columns per block) for the "
          "block-sparse index; `0` disables it like KTPU_BLOCK_INDEX=0. "
          "Unset = the AdaptiveTuner's structural policy row picks the "
          "width from the node count."),
    _flag("KTPU_ADMISSION_WINDOW", None, _parse_ms,
          "Serving admission coalesce window in MILLISECONDS (pinned "
          "for sweeps; `0` = always dispatch immediately). Unset = the "
          "AdaptiveTuner policy row sizes it."),
    _flag("KTPU_TRACE_THRESHOLD_MS", None, _parse_float,
          "Slow-attempt threshold in ms: root span trees and attempt "
          "traces slower than this log a step breakdown. Unset = no "
          "tree dumps; the scheduler's per-attempt logger falls back "
          "to the reference's 100 ms."),
    _flag("KTPU_DATA_DIR", None, _parse_str,
          "Durability directory (WAL + snapshots); the apiserver "
          "recovers state from it on construction when set."),
    _flag("KTPU_LOCK_CHECK", False, _parse_bool,
          "Runtime lock-order / dispatch-hygiene detector "
          "(utils/locking.py): instrumented locks record per-thread "
          "acquisition order and raise on observed inversions and on "
          "locks held across device-fetch/wire-send seams. Off = "
          "plain `threading.Lock`, zero overhead."),
    _flag("KTPU_DEBUG_FREEZE", False, _parse_bool,
          "Recursively freeze stored/watch-delivered objects so a "
          "mutating handler fails loudly (enabled by the test suite)."),
    _flag("KTPU_TEST_PLATFORM", "cpu", _parse_str,
          "jax platform the test suite runs against (tests/conftest.py; "
          "set to run the suite on real hardware)."),
)}


def get(name: str) -> Any:
    """Parsed live read of a registered flag (unset/empty/malformed →
    the registered default). KeyError on unregistered names — a typo'd
    flag read should fail loudly, same contract as the static pass."""
    return FLAGS[name].read()


def get_raw(name: str) -> str | None:
    """The raw environ value of a registered flag (None when unset)."""
    FLAGS[name]  # unregistered names fail loudly here too
    return os.environ.get(name)


@contextmanager
def scoped_set(name: str, value):
    """Set a flag for the duration of a block, restoring the previous
    value (or unset state) on exit — the save/restore idiom PerfRunner
    uses to scope a shard-count override to one run."""
    FLAGS[name]
    prev = os.environ.get(name)
    os.environ[name] = str(value)
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prev


def render_markdown_table() -> str:
    """The README "Flags" table, generated — the flag pass fails when
    the README's copy drifts from this render."""
    lines = [
        "| Flag | Default | Kill switch | What it does |",
        "|---|---|---|---|",
    ]
    for f in FLAGS.values():
        default = "unset" if f.default is None else str(f.default)
        ks = "yes" if f.kill_switch else ""
        doc = " ".join(f.doc.split())
        lines.append(f"| `{f.name}` | `{default}` | {ks} | {doc} |")
    return "\n".join(lines)
