"""OTel-style request tracing (SURVEY §5.1 component-base/tracing).

A lightweight in-process tracer: spans with trace/span ids, parentage
via contextvars (so nested awaits auto-parent), W3C `traceparent`
propagation for cross-component HTTP hops, and export to the Chrome
trace-event JSON that Perfetto (and chrome://tracing) loads — the same
timeline family the jax profiler emits, so a control-plane trace and a
device trace can sit side by side.

Where spans come from:
- APIServer: one span per request (verb/resource/user/status), child
  spans for store ops and admission webhook out-calls;
- Scheduler: a span per scheduling attempt and per binding cycle,
  attributed with the pod key;
- anything else via `TRACER.span(...)` / `aspan(...)`.

The pod's journey (create → schedule → bind) crosses async boundaries
the context can't follow (informer → queue → cycle), so spans carry a
`pod` attribute and `trace_for(pod_key)` assembles the cross-component
story — the reference's kube-apiserver + kube-scheduler traces joined
on object identity.

Disabled by default: a disabled tracer's span() is a no-op costing one
attribute check, so the hot paths stay clean (utiltrace remains the
always-on threshold logger).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import logging
import time
from typing import Any

from kubernetes_tpu.utils import flags

logger = logging.getLogger(__name__)

_ids = itertools.count(1)
_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "ktpu_current_span", default=None)

#: pod-annotation key carrying the creating request's traceparent across
#: the informer/queue async boundary (the context can't follow a pod from
#: the apiserver handler to the scheduling cycle; the object can).
TRACEPARENT_ANNOTATION = "ktpu.io/traceparent"


def current_span() -> "Span | None":
    """The span the calling context is inside, if any (shared across all
    Tracer instances — parentage is a property of the call stack, not of
    the collector)."""
    return _current.get()


def stamp_traceparent(obj: dict) -> None:
    """Stamp the current span's traceparent into `obj`'s annotations so a
    later consumer in another task (the scheduler's attempt span) can
    parent to the request that created the object. No-op outside a span,
    so call sites need no enabled-check of their own."""
    sp = _current.get()
    if sp is None:
        return
    meta = obj.setdefault("metadata", {})
    ann = meta.get("annotations")
    if ann is None:
        ann = meta["annotations"] = {}
    ann.setdefault(TRACEPARENT_ANNOTATION,
                   format_traceparent(sp.trace_id, sp.span_id))


def traceparent_of(obj: dict | None) -> str | None:
    """Read a stamped traceparent back off an object (see
    stamp_traceparent)."""
    if not obj:
        return None
    ann = (obj.get("metadata") or {}).get("annotations")
    if not ann:
        return None
    return ann.get(TRACEPARENT_ANNOTATION)


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "end", "attrs")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: str | None, attrs: dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = time.monotonic()
        self.end: float | None = None
        self.attrs = attrs

    @property
    def duration_ms(self) -> float:
        return 1000.0 * ((self.end or time.monotonic()) - self.start)


class Tracer:
    """Span collector. Bounded ring (oldest spans drop) so an always-on
    tracer can't grow without limit.

    `threshold_ms` is the utiltrace-semantics dump: when a ROOT span (no
    parent — e.g. a request arriving with no traceparent) closes slower
    than the threshold, its whole subtree logs as an indented breakdown;
    fast roots stay silent. Defaults from KTPU_TRACE_THRESHOLD_MS
    (unset = no tree dumps; the always-on per-attempt threshold logger
    remains utils/trace.Trace)."""

    def __init__(self, enabled: bool = False, max_spans: int = 65536,
                 threshold_ms: float | None = None):
        from collections import deque
        self.enabled = enabled
        self.max_spans = max_spans
        if threshold_ms is None:
            threshold_ms = flags.get("KTPU_TRACE_THRESHOLD_MS")
        self.threshold_ms = threshold_ms
        # deque(maxlen): O(1) ring-buffer appends — a full list ring
        # would memmove 64k entries per span on the hot path.
        self.spans: "deque[Span]" = deque(maxlen=max_spans)

    # -- span creation -----------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, *, traceparent: str | None = None,
             **attrs: Any):
        """Sync/async-agnostic context manager (works under `async with
        tracer.aspan(...)` too via the wrapper below)."""
        if not self.enabled:
            yield None
            return
        parent = _current.get()
        if traceparent:
            trace_id, parent_id = _parse_traceparent(traceparent)
        elif parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = f"t{next(_ids):016x}", None
        sp = Span(name, trace_id, f"s{next(_ids):08x}", parent_id, attrs)
        self.spans.append(sp)  # maxlen ring: oldest drops automatically
        token = _current.set(sp)
        try:
            yield sp
        finally:
            sp.end = time.monotonic()
            _current.reset(token)
            if self.threshold_ms is not None and sp.parent_id is None \
                    and sp.duration_ms >= self.threshold_ms:
                self._log_tree(sp)

    @contextlib.asynccontextmanager
    async def aspan(self, name: str, **kw):
        with self.span(name, **kw) as sp:
            yield sp

    def annotate(self, **attrs: Any) -> None:
        """Attach attributes to the CURRENT span (e.g. the pod key a
        create request turns out to be about, known only after the body
        parses)."""
        if not self.enabled:
            return
        sp = _current.get()
        if sp is not None:
            sp.attrs.update(attrs)

    def record(self, name: str, start: float, end: float | None = None,
               **attrs: Any) -> "Span | None":
        """Retroactively record a COMPLETED span from caller-held
        timestamps (time.monotonic clock), parented to the current span —
        e.g. the scheduler's queue wait, which elapses across tasks no
        context can follow but whose endpoints the queue stamped."""
        if not self.enabled:
            return None
        parent = _current.get()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = f"t{next(_ids):016x}", None
        sp = Span(name, trace_id, f"s{next(_ids):08x}", parent_id, attrs)
        sp.start = start
        sp.end = end if end is not None else time.monotonic()
        self.spans.append(sp)
        return sp

    def current_traceparent(self) -> str | None:
        sp = _current.get()
        if sp is None:
            return None
        return format_traceparent(sp.trace_id, sp.span_id)

    # -- threshold tree dump (utiltrace semantics for span trees) ----------

    def _log_tree(self, root: Span) -> None:
        by_parent: dict[str, list[Span]] = {}
        for s in self.spans:
            if s.trace_id == root.trace_id and s.parent_id:
                by_parent.setdefault(s.parent_id, []).append(s)
        attrs = ",".join(f"{k}={v}" for k, v in root.attrs.items())
        lines = [f"Span[{root.name}{{{attrs}}}]: "
                 f"total {root.duration_ms:.1f}ms" if attrs else
                 f"Span[{root.name}]: total {root.duration_ms:.1f}ms"]

        def walk(sp: Span, depth: int) -> None:
            for child in sorted(by_parent.get(sp.span_id, ()),
                                key=lambda s: s.start):
                a = ",".join(f"{k}={v}" for k, v in child.attrs.items())
                lines.append(f'{"  " * depth}{child.name}'
                             f'{"{" + a + "}" if a else ""} '
                             f"{child.duration_ms:.1f}ms")
                walk(child, depth + 1)

        walk(root, 1)
        logger.info("\n".join(lines))

    # -- queries + export --------------------------------------------------

    def trace_for(self, pod_key: str) -> list[Span]:
        """Every span attributed to one pod, time-ordered — the
        cross-component create→schedule→bind story."""
        return sorted((s for s in self.spans
                       if s.attrs.get("pod") == pod_key),
                      key=lambda s: s.start)

    def to_perfetto(self) -> str:
        """Chrome trace-event JSON (Perfetto/chrome://tracing/the jax
        profiler's timeline family). Complete ('X') events in µs."""
        events = []
        for s in self.spans:
            if s.end is None:
                continue
            events.append({
                "name": s.name, "ph": "X", "pid": 1,
                "tid": abs(hash(s.trace_id)) % 100_000,
                "ts": round(s.start * 1e6, 3),
                "dur": round((s.end - s.start) * 1e6, 3),
                "args": {**{k: str(v) for k, v in s.attrs.items()},
                         "trace_id": s.trace_id, "span_id": s.span_id,
                         **({"parent_id": s.parent_id}
                            if s.parent_id else {})},
            })
        return json.dumps({"traceEvents": events}, separators=(",", ":"))

    def clear(self) -> None:
        self.spans.clear()


def format_traceparent(trace_id: str, span_id: str) -> str:
    # W3C shape (version-trace-parent-flags); ids are our own tokens.
    return f"00-{trace_id}-{span_id}-01"


def _parse_traceparent(header: str) -> tuple[str, str | None]:
    # Tolerate garbage (wrong type, malformed): propagation input comes
    # off the wires, and a bad header must degrade to a fresh trace, not
    # crash the serving path.
    parts = header.split("-") if isinstance(header, str) else ()
    if len(parts) >= 3:
        return parts[1], parts[2]
    return f"t{next(_ids):016x}", None


#: process-wide default; enable with DEFAULT_TRACER.enabled = True.
DEFAULT_TRACER = Tracer(enabled=False)
