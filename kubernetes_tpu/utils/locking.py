"""Runtime lock-order and dispatch-hygiene detector (`KTPU_LOCK_CHECK=1`).

The static lock pass (`kubernetes_tpu/analysis/locks.py`) extracts the
lock-acquisition graph from `with self._lock:` sites at analysis time;
this module is its runtime twin, so the two cross-validate: the static
pass proves properties of the code as written, the detector catches
whatever dynamic dispatch, monkeypatching or threading reality the AST
cannot see.

`new_lock(name)` is the only constructor the tree uses. With the flag
off (the default) it returns a plain `threading.Lock` — ZERO overhead,
nothing imported on the hot path, no bookkeeping. With
`KTPU_LOCK_CHECK=1` (enabled for the tier-1 serving and watch-cache
smoke suites) it returns an `InstrumentedLock` that

- records the per-thread acquisition stack and the global observed
  order graph (directed edges outer→inner, keyed by lock NAME so
  instances of one class alias to one node);
- raises `LockOrderError` the moment an acquisition INVERTS an edge
  observed earlier (the classic ABBA deadlock, caught on first
  occurrence instead of on the unlucky interleaving);
- backs `check_dispatch_seam()`: the sanctioned device-fetch and
  wire-send seams call it, and it raises `LockHeldAcrossDispatchError`
  when the calling thread still holds any instrumented lock — a lock
  held across a device round-trip or a socket write is a stall the
  static pass also hunts (LK203/LK204).

`check_dispatch_seam` is free when nothing is instrumented: it reads
one thread-local and returns — no env read, no branch on flag state —
so it can sit on per-chunk and per-frame paths unconditionally.
"""

from __future__ import annotations

import threading
import traceback

from kubernetes_tpu.utils import flags

__all__ = ["InstrumentedLock", "LockOrderError",
           "LockHeldAcrossDispatchError", "new_lock",
           "check_dispatch_seam", "held_locks", "reset_observed"]


class LockOrderError(RuntimeError):
    """An acquisition inverted a previously observed lock order."""


class LockHeldAcrossDispatchError(RuntimeError):
    """A dispatch/fetch/wire-send seam ran with a lock held."""


_tls = threading.local()
#: observed order edges {(outer_name, inner_name): "site"} — guarded by
#: _graph_lock (a PLAIN lock: the detector must not instrument itself).
_edges: dict[tuple[str, str], str] = {}
_graph_lock = threading.Lock()


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


class InstrumentedLock:
    """A `threading.Lock` that records acquisition order per thread.

    Same-NAME nesting is exempt from ordering (many instances share one
    name — e.g. every Counter's `metrics.counter` lock — and ordering
    between interchangeable instances carries no deadlock information
    the name-level graph can express)."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()

    def _record_edges(self) -> None:
        stack = _held_stack()
        if not stack:
            return
        site = "".join(traceback.format_stack(limit=6)[:-2])
        with _graph_lock:
            for outer in stack:
                if outer.name == self.name:
                    continue
                inv = _edges.get((self.name, outer.name))
                if inv is not None:
                    raise LockOrderError(
                        f"lock order inversion: acquiring {self.name!r} "
                        f"while holding {outer.name!r}, but the opposite "
                        f"order ({self.name!r} -> {outer.name!r}) was "
                        f"observed earlier at:\n{inv}")
                _edges.setdefault((outer.name, self.name), site)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # Order is checked BEFORE blocking: an inversion must raise, not
        # deadlock the test run it exists to protect.
        self._record_edges()
        got = self._lock.acquire(blocking, timeout)
        if got:
            _held_stack().append(self)
        return got

    def release(self) -> None:
        stack = _held_stack()
        if self in stack:
            stack.remove(self)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def new_lock(name: str):
    """The tree's lock constructor: a plain `threading.Lock` when the
    detector is off (zero overhead), an `InstrumentedLock` when
    `KTPU_LOCK_CHECK=1` — decided at construction, so long-lived locks
    created inside an enabled test are instrumented for their lifetime."""
    if flags.get("KTPU_LOCK_CHECK"):
        return InstrumentedLock(name)
    return threading.Lock()


def check_dispatch_seam(seam: str) -> None:
    """Raise when the calling thread holds any instrumented lock.

    Called from the sanctioned device-fetch seams (backend chunk fetch,
    fast-path fetch) and the wire send path; free when nothing is held."""
    stack = getattr(_tls, "held", None)
    if not stack:
        return
    names = [lk.name for lk in stack]
    raise LockHeldAcrossDispatchError(
        f"{seam}: dispatch seam entered while holding lock(s) {names} — "
        "a lock held across a device fetch or wire send stalls every "
        "other holder for the round-trip")


def held_locks() -> tuple[str, ...]:
    """Names of instrumented locks held by the calling thread."""
    stack = getattr(_tls, "held", None)
    return tuple(lk.name for lk in stack) if stack else ()


def reset_observed() -> None:
    """Clear the global order graph (test isolation)."""
    with _graph_lock:
        _edges.clear()
