"""utiltrace: threshold latency tracing (k8s.io/utils/trace).

The scheduler wraps each scheduling attempt in a Trace; steps record
named timestamps, and the whole trace is logged ONLY when total latency
crosses the threshold — the reference's "Trace[...] ... (xx ms)" lines
that make slow attempts debuggable without log spam.
"""

from __future__ import annotations

import logging
import time

logger = logging.getLogger("kubernetes_tpu.trace")


class Trace:
    __slots__ = ("name", "fields", "threshold", "_t0", "_steps")

    def __init__(self, name: str, threshold_ms: float = 100.0, **fields):
        self.name = name
        self.fields = fields
        self.threshold = threshold_ms / 1e3
        self._t0 = time.perf_counter()
        self._steps: list[tuple[float, str]] = []

    def step(self, msg: str) -> None:
        self._steps.append((time.perf_counter(), msg))

    def __enter__(self) -> "Trace":
        return self

    def __exit__(self, *exc) -> None:
        self.log()

    def log(self) -> None:
        total = time.perf_counter() - self._t0
        if total < self.threshold:
            return
        fields = ",".join(f"{k}={v}" for k, v in self.fields.items())
        lines = [f'Trace[{self.name}{{{fields}}}]: total {total * 1e3:.1f}ms'
                 if fields else
                 f'Trace[{self.name}]: total {total * 1e3:.1f}ms']
        prev = self._t0
        for ts, msg in self._steps:
            lines.append(f'  step "{msg}" {1e3 * (ts - prev):.1f}ms')
            prev = ts
        logger.info("\n".join(lines))
