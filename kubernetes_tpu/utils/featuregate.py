"""Feature gates (component-base/featuregate `FeatureGate` +
pkg/features/kube_features.go).

`--feature-gates=TPUScorer=true` is north-star seam #3 (SURVEY §5.6): it flips
the scheduler's batched extension points to the tensor backend. Gates carry
Alpha/Beta/GA stages with per-stage defaults, are settable from a spec string,
and are queried at wiring time (not in hot loops).
"""

from __future__ import annotations

ALPHA = "Alpha"
BETA = "Beta"
GA = "GA"
DEPRECATED = "Deprecated"


class FeatureGate:
    def __init__(self):
        self._known: dict[str, tuple[str, bool]] = {}
        self._enabled: dict[str, bool] = {}

    def add(self, name: str, stage: str, default: bool) -> None:
        self._known[name] = (stage, default)

    def enabled(self, name: str) -> bool:
        if name not in self._known:
            raise KeyError(f"unknown feature gate {name!r}")
        if name in self._enabled:
            return self._enabled[name]
        return self._known[name][1]

    def set(self, name: str, value: bool) -> None:
        if name not in self._known:
            raise KeyError(f"unknown feature gate {name!r}")
        stage, _ = self._known[name]
        if stage == GA and not value:
            raise ValueError(f"cannot disable GA feature {name!r}")
        self._enabled[name] = value

    def set_from_spec(self, spec: str) -> None:
        """Parse "--feature-gates" syntax: "Name=true,Other=false".

        Unparseable boolean values are an error (component-base featuregate
        `Set` rejects them rather than silently disabling the feature); a
        bare name with no "=" enables, matching Go flag bool semantics.
        """
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, eq, val = part.partition("=")
            val = val.strip().lower()
            if not eq or val == "true":
                value = True
            elif val == "false":
                value = False
            else:
                raise ValueError(
                    f"invalid value {val!r} for feature gate {name.strip()!r}"
                    " (want true|false)")
            self.set(name.strip(), value)

    def known(self) -> dict[str, tuple[str, bool]]:
        return dict(self._known)

    def clone(self) -> "FeatureGate":
        """Independent copy — per-component gate resolution must not leak
        into the process-wide defaults."""
        g = FeatureGate()
        g._known = dict(self._known)
        g._enabled = dict(self._enabled)
        return g


#: Process-wide default gate set (kube_features.go `defaultKubernetesFeatureGates`).
DEFAULT_FEATURE_GATES = FeatureGate()
DEFAULT_FEATURE_GATES.add("TPUScorer", ALPHA, False)
DEFAULT_FEATURE_GATES.add("TPUBatchSolver", ALPHA, False)
DEFAULT_FEATURE_GATES.add("SchedulerQueueingHints", BETA, True)
DEFAULT_FEATURE_GATES.add("PodSchedulingGates", GA, True)
