"""kubernetes_tpu — a TPU-native cluster-scheduling control plane.

A from-scratch framework with the capabilities of the Kubernetes control plane
(reference: mjg59/kubernetes), redesigned TPU-first:

- A declarative, watch-driven object store (``kubernetes_tpu.store``) with
  ResourceVersion / LIST+WATCH semantics — the hub every component talks through.
- A client layer (``kubernetes_tpu.client``) reproducing the
  reflector → informer → workqueue triangle every controller uses.
- A scheduler (``kubernetes_tpu.scheduler``) exposing the same extension-point
  framework (PreFilter/Filter/PostFilter/Score/Reserve/Permit/Bind...) as the
  reference's pkg/scheduler/framework, but whose execution backend recasts the
  per-pod Filter/Score loop as a batched (pods × nodes) tensor program
  (``kubernetes_tpu.ops``) solved on TPU via XLA, sharded over a device mesh
  (``kubernetes_tpu.parallel``).
- Controllers (``kubernetes_tpu.controllers``) for workload and node lifecycle.

Reference citations in docstrings use upstream Kubernetes paths + symbols
(see SURVEY.md PROVENANCE: the reference mount was empty; symbols are the
stable public layout of kubernetes/kubernetes which mjg59/kubernetes forks).
"""

__version__ = "0.1.0"
