"""Resource quantities.

Capability parity with the reference's resource.Quantity
(staging/src/k8s.io/apimachinery/pkg/api/resource/quantity.go: `Quantity`,
`ParseQuantity`, `MilliValue`): parse/format the Kubernetes quantity grammar —
decimal SI suffixes (k, M, G, T, P, E), binary suffixes (Ki, Mi, Gi, Ti, Pi, Ei),
milli ("500m"), bare integers and decimals ("0.5", "2e3").

TPU-first deviation: instead of the reference's infinite-precision decimal with
cached scaled ints, we canonicalize every quantity to an **integer milli-value**
(int64-safe for realistic cluster sizes). All scheduler math then happens on
integer/float tensors; string round-tripping is only for the API surface. This is
what lets a node's allocatable vector become one row of an (N × R) int array.
"""

from __future__ import annotations

import re
from typing import Union

_BIN = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DEC = {
    "n": 10**-9, "u": 10**-6, "m": 10**-3, "": 1,
    "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18,
}

_QTY_RE = re.compile(
    r"^\s*([+-]?\d+(?:\.\d*)?(?:[eE][+-]?\d+)?)\s*"
    r"(Ki|Mi|Gi|Ti|Pi|Ei|n|u|m|k|M|G|T|P|E)?\s*$"
)


#: Memo for string quantities: workloads come from pod templates, so a
#: handful of distinct strings are parsed millions of times at perf scale.
_PARSE_CACHE: dict[str, int] = {}
_PARSE_CACHE_MAX = 4096


def parse_quantity(s: Union[str, int, float, None]) -> int:
    """Parse a quantity into integer milli-units.

    "1" → 1000, "500m" → 500, "2Gi" → 2*2**30*1000, 1.5 → 1500.
    None/"" → 0. Raises ValueError on malformed input (the reference's
    ParseQuantity errors likewise).
    """
    if s is None or s == "":
        return 0
    if isinstance(s, bool):
        raise ValueError(f"invalid quantity: {s!r}")
    if isinstance(s, int):
        return s * 1000
    if isinstance(s, float):
        return round(s * 1000)
    cached = _PARSE_CACHE.get(s)
    if cached is not None:
        return cached
    m = _QTY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {s!r}")
    num, suffix = m.group(1), m.group(2) or ""
    if suffix in _BIN:
        mult = _BIN[suffix]
    else:
        mult = _DEC[suffix]
    val = round(float(num) * mult * 1000)
    if len(_PARSE_CACHE) < _PARSE_CACHE_MAX:
        _PARSE_CACHE[s] = val
    return val


def format_quantity(milli: int) -> str:
    """Format integer milli-units back to a canonical quantity string.

    Whole units print bare ("2"); sub-unit values print in milli ("500m").
    Large byte-ish values are NOT re-suffixed (canonicalization to suffixes is
    cosmetic; the reference also accepts any equivalent form).
    """
    if milli % 1000 == 0:
        return str(milli // 1000)
    return f"{milli}m"


class Quantity:
    """Thin value wrapper, mostly for tests/debugging; hot paths use raw ints."""

    __slots__ = ("milli",)

    def __init__(self, value: Union[str, int, float, "Quantity", None] = 0):
        if isinstance(value, Quantity):
            self.milli = value.milli
        else:
            self.milli = parse_quantity(value)

    def value(self) -> float:
        return self.milli / 1000

    def milli_value(self) -> int:
        return self.milli

    def __add__(self, other: "Quantity") -> "Quantity":
        q = Quantity()
        q.milli = self.milli + Quantity(other).milli
        return q

    def __sub__(self, other: "Quantity") -> "Quantity":
        q = Quantity()
        q.milli = self.milli - Quantity(other).milli
        return q

    def __eq__(self, other) -> bool:
        return isinstance(other, (Quantity, str, int, float)) and Quantity(other).milli == self.milli

    def __lt__(self, other) -> bool:
        return self.milli < Quantity(other).milli

    def __le__(self, other) -> bool:
        return self.milli <= Quantity(other).milli

    def __hash__(self) -> int:
        return hash(self.milli)

    def __repr__(self) -> str:
        return f"Quantity({format_quantity(self.milli)!r})"

    def __str__(self) -> str:
        return format_quantity(self.milli)


def parse_resource_list(resources: dict | None) -> dict[str, int]:
    """Parse a ResourceList ({"cpu": "500m", "memory": "1Gi"}) → {name: milli}."""
    if not resources:
        return {}
    return {name: parse_quantity(v) for name, v in resources.items()}
