"""Object metadata helpers over wire-shape dicts.

Parity target: staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/types.go
(`ObjectMeta`: name/namespace/uid/resourceVersion/labels/annotations/
ownerReferences/creationTimestamp/deletionTimestamp/finalizers).

API objects in this framework ARE their wire form: plain nested dicts with
camelCase keys, exactly what the reference serializes to JSON. That choice makes
the store trivially serializable, lets reference YAML load unchanged, and avoids
a conversion layer (the reference's internal-hub-type machinery exists to manage
N wire versions; we have one).
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Any, Mapping

# uuid4() costs an os.urandom syscall per call — at scheduler_perf scale
# (every pod + every Event records a uid) it was >50% of the measured-phase
# wall on one core. One random 64-bit boot epoch + a process-local counter
# keeps uids unique across restarts at ~30ns each.
_UID_EPOCH = os.urandom(8).hex()
_UID_SEQ = itertools.count(1)


def new_uid() -> str:
    return f"{_UID_EPOCH}-{next(_UID_SEQ):x}"


def new_object(
    kind: str,
    name: str,
    namespace: str | None = "default",
    labels: Mapping[str, str] | None = None,
    annotations: Mapping[str, str] | None = None,
    api_version: str = "v1",
    **spec_fields: Any,
) -> dict:
    """Build a minimal API object dict with populated metadata."""
    meta: dict[str, Any] = {"name": name, "uid": new_uid()}
    if namespace is not None:
        meta["namespace"] = namespace
    if labels:
        meta["labels"] = dict(labels)
    if annotations:
        meta["annotations"] = dict(annotations)
    obj: dict[str, Any] = {"apiVersion": api_version, "kind": kind, "metadata": meta}
    obj.update(spec_fields)
    return obj


def name_of(obj: Mapping) -> str:
    return obj.get("metadata", {}).get("name", "")


def namespace_of(obj: Mapping) -> str:
    return obj.get("metadata", {}).get("namespace", "")


def uid_of(obj: Mapping) -> str:
    return obj.get("metadata", {}).get("uid", "")


def labels_of(obj: Mapping) -> dict:
    return obj.get("metadata", {}).get("labels") or {}


def annotations_of(obj: Mapping) -> dict:
    return obj.get("metadata", {}).get("annotations") or {}


def resource_version_of(obj: Mapping) -> int:
    rv = obj.get("metadata", {}).get("resourceVersion", "0")
    return int(rv) if rv else 0


def namespaced_name(obj: Mapping) -> str:
    """"ns/name" key, or bare name for cluster-scoped objects (e.g. Node)."""
    ns = namespace_of(obj)
    return f"{ns}/{name_of(obj)}" if ns else name_of(obj)


def owner_references_of(obj: Mapping) -> list:
    return obj.get("metadata", {}).get("ownerReferences") or []


def controller_ref_of(obj: Mapping) -> dict | None:
    """The single controller=true ownerReference, if any
    (metav1.GetControllerOf)."""
    for ref in owner_references_of(obj):
        if ref.get("controller"):
            return ref
    return None


def new_controller_ref(owner: Mapping, kind: str | None = None) -> dict:
    """metav1.NewControllerRef equivalent."""
    return {
        "apiVersion": owner.get("apiVersion", "v1"),
        "kind": kind or owner.get("kind", ""),
        "name": name_of(owner),
        "uid": uid_of(owner),
        "controller": True,
        "blockOwnerDeletion": True,
    }


def now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def set_creation_timestamp(obj: dict) -> None:
    obj.setdefault("metadata", {}).setdefault("creationTimestamp", now_iso())


def deep_copy(obj: Any) -> Any:
    """Structure-aware deep copy for wire objects (dicts/lists/scalars only).

    Much faster than copy.deepcopy for this shape; the store hands copies out so
    callers can't mutate cached state (the reference relies on Go value
    semantics + informer "never mutate cache objects" convention instead).
    """
    if isinstance(obj, dict):
        return {k: deep_copy(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [deep_copy(v) for v in obj]
    return obj


#: kind → store resource, the one shared mapping (CLI apply/delete, the
#: garbage collector's owner lookup, and the API server all key off it).
KIND_TO_RESOURCE = {
    "Pod": "pods", "Node": "nodes", "Namespace": "namespaces",
    "Deployment": "deployments", "ReplicaSet": "replicasets",
    "StatefulSet": "statefulsets", "DaemonSet": "daemonsets",
    "Job": "jobs", "PodGroup": "podgroups",
    "PersistentVolume": "persistentvolumes",
    "PersistentVolumeClaim": "persistentvolumeclaims",
    "StorageClass": "storageclasses",
    "NodeResourceTopology": "noderesourcetopologies",
    "Service": "services", "Event": "events", "Lease": "leases",
    "EndpointSlice": "endpointslices",
    "ResourceQuota": "resourcequotas",
    "PodDisruptionBudget": "poddisruptionbudgets",
    "HorizontalPodAutoscaler": "horizontalpodautoscalers",
    # DRA (resource.k8s.io structured parameters — SURVEY §2.3
    # dynamicresources/, §2.5 devicemanager): the modern device path.
    "ResourceClaim": "resourceclaims",
    "ResourceClaimTemplate": "resourceclaimtemplates",
    "DeviceClass": "deviceclasses",
    "ResourceSlice": "resourceslices",
    "CronJob": "cronjobs",
    "ServiceAccount": "serviceaccounts",
    "Secret": "secrets",
    "VolumeAttachment": "volumeattachments",
    "ConfigMap": "configmaps",
    # admissionregistration.k8s.io expression policies (policy/vap.py).
    "ValidatingAdmissionPolicy": "validatingadmissionpolicies",
    "ValidatingAdmissionPolicyBinding": "validatingadmissionpolicybindings",
}

#: resources without a namespace segment in their keys/URLs.
CLUSTER_SCOPED_RESOURCES = {
    "nodes", "namespaces", "persistentvolumes", "storageclasses",
    "noderesourcetopologies", "deviceclasses", "resourceslices",
    "volumeattachments", "validatingadmissionpolicies",
    "validatingadmissionpolicybindings",
}
