"""API machinery: object model, resource quantities, label selectors.

Mirrors the *capabilities* of the reference's apimachinery + api staging repos
(staging/src/k8s.io/apimachinery, staging/src/k8s.io/api) without the Go type
system: API objects are plain dicts in Kubernetes wire shape (camelCase keys),
so reference manifests/YAML load unchanged. Typed accessors live beside them.
"""

from kubernetes_tpu.api.resource import Quantity, parse_quantity, format_quantity
from kubernetes_tpu.api.labels import (
    Selector,
    match_label_selector,
    parse_selector,
)
from kubernetes_tpu.api.meta import (
    name_of,
    namespace_of,
    namespaced_name,
    uid_of,
    labels_of,
    new_object,
)

__all__ = [
    "Quantity",
    "parse_quantity",
    "format_quantity",
    "Selector",
    "match_label_selector",
    "parse_selector",
    "name_of",
    "namespace_of",
    "namespaced_name",
    "uid_of",
    "labels_of",
    "new_object",
]
