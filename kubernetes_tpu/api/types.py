"""Core API object builders + typed accessors for Pod / Node / Binding.

Parity target: staging/src/k8s.io/api/core/v1/types.go (`Pod`, `PodSpec` —
nodeName, schedulerName, affinity, tolerations, topologySpreadConstraints,
resources, priority, schedulingGates, overhead; `Node`, `NodeSpec.taints`,
`NodeStatus.allocatable`; `Binding`) and the pod resource-request helpers in
pkg/api/v1/resource/helpers.go (`PodRequests`: max(initContainers) folded with
sum(containers), plus pod overhead).

Objects remain wire-shape dicts (see api.meta); this module provides the
constructors used across tests/controllers and the semantics-bearing accessors
the scheduler compiles its tensors from.
"""

from __future__ import annotations

from typing import Any, Mapping

from kubernetes_tpu.api.meta import new_object
from kubernetes_tpu.api.resource import parse_resource_list

# Canonical resource names (core/v1 const ResourceCPU etc.)
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

DEFAULT_SCHEDULER_NAME = "default-scheduler"

# Implicit non-zero request applied when a container specifies no request, so
# that scoring spreads pods sensibly (the reference applies the same defaults in
# scheduler scoring only: pkg/scheduler/util/pod_resources.go
# `DefaultMilliCPURequest`=100m, `DefaultMemoryRequest`=200Mi).
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST_MILLI = 200 * 1024 * 1024 * 1000


def make_pod(
    name: str,
    namespace: str = "default",
    labels: Mapping[str, str] | None = None,
    requests: Mapping[str, Any] | None = None,
    limits: Mapping[str, Any] | None = None,
    node_name: str | None = None,
    priority: int | None = None,
    scheduler_name: str = DEFAULT_SCHEDULER_NAME,
    affinity: Mapping | None = None,
    tolerations: list | None = None,
    node_selector: Mapping[str, str] | None = None,
    topology_spread_constraints: list | None = None,
    scheduling_gates: list | None = None,
    host_ports: list[int] | None = None,
    phase: str = "Pending",
    uid: str | None = None,
    resource_claims: list | None = None,
) -> dict:
    container: dict[str, Any] = {"name": "main", "image": "app"}
    res: dict[str, Any] = {}
    if requests:
        res["requests"] = dict(requests)
    if limits:
        res["limits"] = dict(limits)
    if res:
        container["resources"] = res
    if host_ports:
        container["ports"] = [{"hostPort": p, "protocol": "TCP"} for p in host_ports]
    spec: dict[str, Any] = {"containers": [container], "schedulerName": scheduler_name}
    if node_name:
        spec["nodeName"] = node_name
    if priority is not None:
        spec["priority"] = priority
    if affinity:
        spec["affinity"] = dict(affinity)
    if tolerations:
        spec["tolerations"] = list(tolerations)
    if node_selector:
        spec["nodeSelector"] = dict(node_selector)
    if topology_spread_constraints:
        spec["topologySpreadConstraints"] = list(topology_spread_constraints)
    if scheduling_gates:
        spec["schedulingGates"] = [{"name": g} for g in scheduling_gates]
    if resource_claims:
        # DRA claim references: [{"name": ..., "resourceClaimName": ...}]
        # or {"resourceClaimTemplateName": ...} entries.
        spec["resourceClaims"] = list(resource_claims)
    pod = new_object("Pod", name, namespace, labels=labels, spec=spec,
                     status={"phase": phase})
    if uid:
        pod["metadata"]["uid"] = uid
    return pod


def make_node(
    name: str,
    labels: Mapping[str, str] | None = None,
    allocatable: Mapping[str, Any] | None = None,
    capacity: Mapping[str, Any] | None = None,
    taints: list | None = None,
    unschedulable: bool = False,
    images: list | None = None,
) -> dict:
    alloc = dict(allocatable or {"cpu": "8", "memory": "32Gi", "pods": "110"})
    cap = dict(capacity or alloc)
    all_labels = {"kubernetes.io/hostname": name}
    if labels:
        all_labels.update(labels)
    spec: dict[str, Any] = {}
    if taints:
        spec["taints"] = list(taints)
    if unschedulable:
        spec["unschedulable"] = True
    status: dict[str, Any] = {
        "allocatable": alloc,
        "capacity": cap,
        "conditions": [{"type": "Ready", "status": "True"}],
    }
    if images:
        status["images"] = images
    node = new_object("Node", name, namespace=None, labels=all_labels,
                      spec=spec, status=status)
    return node


def make_pv(name: str, capacity: str = "10Gi", *,
            storage_class: str = "", access_modes: list | None = None,
            node_affinity: Mapping | None = None,
            labels: Mapping[str, str] | None = None,
            reclaim_policy: str = "Retain") -> dict:
    """core/v1 PersistentVolume (cluster-scoped). `node_affinity` is the
    PV's `spec.nodeAffinity.required` nodeSelectorTerms mapping (topology
    pinning — local/zonal volumes)."""
    spec = {
        "capacity": {"storage": capacity},
        "accessModes": access_modes or ["ReadWriteOnce"],
        "storageClassName": storage_class,
        "persistentVolumeReclaimPolicy": reclaim_policy,
    }
    if node_affinity:
        spec["nodeAffinity"] = {"required": dict(node_affinity)}
    pv = new_object("PersistentVolume", name, None, spec=spec,
                    status={"phase": "Available"})
    if labels:
        pv["metadata"]["labels"] = dict(labels)
    return pv


def make_pvc(name: str, namespace: str = "default", request: str = "1Gi", *,
             storage_class: str | None = None,
             access_modes: list | None = None) -> dict:
    spec = {
        "resources": {"requests": {"storage": request}},
        "accessModes": access_modes or ["ReadWriteOnce"],
    }
    if storage_class is not None:
        spec["storageClassName"] = storage_class
    return new_object("PersistentVolumeClaim", name, namespace, spec=spec,
                      status={"phase": "Pending"})


def make_storage_class(name: str, *,
                       binding_mode: str = "Immediate",
                       provisioner: str = "ktpu.dev/simulated",
                       allowed_topologies: list | None = None,
                       is_default: bool = False) -> dict:
    """storage.k8s.io/v1 StorageClass; `binding_mode` is
    Immediate | WaitForFirstConsumer. `is_default` sets the
    storageclass.kubernetes.io/is-default-class annotation the
    DefaultStorageClass admission mutator looks for."""
    sc = new_object("StorageClass", name, None)
    sc["volumeBindingMode"] = binding_mode
    sc["provisioner"] = provisioner
    if allowed_topologies:
        sc["allowedTopologies"] = allowed_topologies
    if is_default:
        sc["metadata"].setdefault("annotations", {})[
            "storageclass.kubernetes.io/is-default-class"] = "true"
    return sc


def make_device_class(name: str,
                      selectors: Mapping[str, str] | None = None) -> dict:
    """resource.k8s.io DeviceClass (structured parameters). `selectors`
    are attribute equality matchers — the tractable core of the
    reference's CEL selectors (`pkg/apis/resource/types.go DeviceClass`):
    a device belongs to the class iff every (attr, value) pair matches."""
    dc = new_object("DeviceClass", name, None,
                    api_version="resource.k8s.io/v1")
    dc["spec"] = {"selectors": dict(selectors or {})}
    return dc


def make_resource_slice(node_name: str, driver: str,
                        devices: list[dict],
                        name: str | None = None) -> dict:
    """resource.k8s.io ResourceSlice: the per-node device inventory a DRA
    driver publishes (reference `ResourceSlice` / kubelet plugin
    ListAndWatch — SURVEY §2.5 devicemanager). `devices` entries:
    {"name": "tpu-0", "attributes": {"type": "tpu", "numa": "0"}}."""
    rs = new_object("ResourceSlice", name or f"{node_name}-{driver}", None,
                    api_version="resource.k8s.io/v1")
    rs["spec"] = {"nodeName": node_name, "driver": driver,
                  "devices": list(devices)}
    return rs


def template_devices(allocatable: Mapping | None,
                     zones: int = 2) -> list[dict]:
    """Derive a node's DRA device list from its allocatable extended
    resources (names containing '/'), the convention kwok nodes and the
    hollow-kubelet agent share: '/' maps to '--' (dots kept) so two
    vendors' same-suffix resources can't collide in the consumed-device
    set, and devices split into contiguous NUMA-zone blocks (devices
    0..n/z-1 in zone 0, etc. — the alignment MatchAttribute needs)."""
    zones = max(1, zones)
    devices: list[dict] = []
    for res, count in (allocatable or {}).items():
        if "/" not in res:
            continue  # core resources are not devices
        try:
            n = int(str(count))
        except ValueError:
            continue
        prefix = res.replace("/", "--")
        short = res.rsplit("/", 1)[1]
        for k in range(n):
            devices.append({
                "name": f"{prefix}-{k}",
                "attributes": {"type": short,
                               "numa": str(k * zones // n)}})
    return devices


def make_resource_claim(name: str, namespace: str = "default",
                        requests: list[dict] | None = None,
                        constraints: list[dict] | None = None) -> dict:
    """resource.k8s.io ResourceClaim. `requests` entries:
    {"name": "tpus", "deviceClassName": "tpu", "count": 4}; `constraints`
    entries: {"matchAttribute": "numa"} — all allocated devices must agree
    on that attribute (the reference's MatchAttribute constraint; this is
    how single-NUMA alignment is expressed the DRA way)."""
    rc = new_object("ResourceClaim", name, namespace,
                    api_version="resource.k8s.io/v1")
    rc["spec"] = {"devices": {"requests": list(requests or []),
                              "constraints": list(constraints or [])}}
    return rc


def make_resource_claim_template(name: str, namespace: str = "default",
                                 requests: list[dict] | None = None,
                                 constraints: list[dict] | None = None
                                 ) -> dict:
    """ResourceClaimTemplate: per-pod claims stamped out by the
    resourceclaim controller for pods referencing the template."""
    t = new_object("ResourceClaimTemplate", name, namespace,
                   api_version="resource.k8s.io/v1")
    t["spec"] = {"devices": {"requests": list(requests or []),
                             "constraints": list(constraints or [])}}
    return t


def make_node_resource_topology(
        node_name: str,
        zones: list[dict],
        policies: list[str] | None = None) -> dict:
    """topology.node.k8s.io/v1alpha2 NodeResourceTopology (the scheduler-
    plugins NUMA CRD; see plugins/noderesourcetopology.py). `zones` entries:
    {"name": ..., "resources": [{"name": ..., "capacity": ...}, ...]}."""
    nrt = new_object("NodeResourceTopology", node_name, None,
                     api_version="topology.node.k8s.io/v1alpha2")
    nrt["topologyPolicies"] = list(
        policies or ["SingleNUMANodeContainerLevel"])
    nrt["zones"] = zones
    return nrt


def split_node_topology(node_name: str, allocatable: Mapping[str, str],
                        num_zones: int = 2,
                        zoned: tuple[str, ...] = ("cpu",),
                        devices: Mapping[str, int] | None = None) -> dict:
    """Convenience: split a node's allocatable evenly into `num_zones` NUMA
    zones (cpu + extended device resources), the shape a device-manager
    node agent would report."""
    from kubernetes_tpu.api.resource import format_quantity, parse_quantity
    zones = []
    for z in range(num_zones):
        res = []
        for r in zoned:
            if r in allocatable:
                res.append({"name": r, "capacity": format_quantity(
                    parse_quantity(allocatable[r]) // num_zones)})
        for r, per_zone in (devices or {}).items():
            res.append({"name": r, "capacity": str(per_zone)})
        zones.append({"name": f"{node_name}-numa-{z}", "type": "Node",
                      "resources": res})
    return make_node_resource_topology(node_name, zones)


def make_namespace(name: str) -> dict:
    """core/v1 Namespace (deletion fans out via NamespaceController)."""
    return new_object("Namespace", name, None, status={"phase": "Active"})


def make_validating_admission_policy(
        name: str,
        validations: list[Mapping],
        *,
        failure_policy: str = "Fail",
        param_kind: str | None = None,
        match_constraints: Mapping | None = None) -> dict:
    """admissionregistration.k8s.io/v1 ValidatingAdmissionPolicy
    (policy/vap.py). `validations` entries: {"expression": ...,
    "message": ...}; `match_constraints` carries resourceRules /
    namespaceSelector. Inert until a binding references it."""
    spec: dict[str, Any] = {
        "failurePolicy": failure_policy,
        "validations": [dict(v) for v in validations],
    }
    if param_kind:
        spec["paramKind"] = {"kind": param_kind}
    if match_constraints is not None:
        spec["matchConstraints"] = dict(match_constraints)
    return new_object("ValidatingAdmissionPolicy", name, None,
                      api_version="admissionregistration.k8s.io/v1",
                      spec=spec)


def make_vap_binding(name: str, policy_name: str, *,
                     param_ref: Mapping | None = None) -> dict:
    """ValidatingAdmissionPolicyBinding: activates a policy; `param_ref`
    ({"name": ..., "namespace": ...}) resolves against the policy's
    paramKind."""
    spec: dict[str, Any] = {"policyName": policy_name}
    if param_ref is not None:
        spec["paramRef"] = dict(param_ref)
    return new_object("ValidatingAdmissionPolicyBinding", name, None,
                      api_version="admissionregistration.k8s.io/v1",
                      spec=spec)


def make_config_map(name: str, namespace: str = "default",
                    data: Mapping[str, Any] | None = None) -> dict:
    """core/v1 ConfigMap — the usual VAP paramKind."""
    return new_object("ConfigMap", name, namespace,
                      data=dict(data or {}))


def make_binding(pod: Mapping, node_name: str) -> dict:
    """core/v1 Binding: target node for a pod; POSTed to the pod's /binding
    subresource (pkg/registry/core/pod/storage `BindingREST.Create`)."""
    return {
        "apiVersion": "v1",
        "kind": "Binding",
        "metadata": {
            "name": pod["metadata"]["name"],
            "namespace": pod["metadata"].get("namespace", "default"),
            "uid": pod["metadata"].get("uid", ""),
        },
        "target": {"kind": "Node", "name": node_name},
    }


# ---------------------------------------------------------------------------
# Pod resource accounting
# ---------------------------------------------------------------------------

def container_requests(container: Mapping) -> dict[str, int]:
    return parse_resource_list((container.get("resources") or {}).get("requests"))


def pod_requests(pod: Mapping, *, non_zero: bool = False) -> dict[str, int]:
    """Effective pod resource requests in milli-units.

    PodRequests semantics (pkg/api/v1/resource/helpers.go): elementwise
    sum over containers, folded with elementwise max over initContainers
    (init containers run serially before the main ones), plus spec.overhead.

    With non_zero=True, cpu/memory get the scheduler's implicit defaults when
    absent (used for Score only, never Filter — matching
    pkg/scheduler/util/pod_resources.go `GetNonzeroRequests`).
    """
    spec = pod.get("spec", {})
    total: dict[str, int] = {}
    for c in spec.get("containers") or []:
        for r, v in container_requests(c).items():
            total[r] = total.get(r, 0) + v
    for c in spec.get("initContainers") or []:
        for r, v in container_requests(c).items():
            if v > total.get(r, 0):
                total[r] = v
    for r, v in parse_resource_list(spec.get("overhead")).items():
        total[r] = total.get(r, 0) + v
    if non_zero:
        if total.get(CPU, 0) == 0:
            total[CPU] = DEFAULT_MILLI_CPU_REQUEST
        if total.get(MEMORY, 0) == 0:
            total[MEMORY] = DEFAULT_MEMORY_REQUEST_MILLI
    return total


def pod_host_ports(pod: Mapping) -> list[tuple[str, str, int]]:
    """(ip, protocol, port) triples claimed by the pod's containers."""
    out = []
    for c in pod.get("spec", {}).get("containers") or []:
        for p in c.get("ports") or []:
            hp = p.get("hostPort")
            if hp:
                out.append((p.get("hostIP", "0.0.0.0"), p.get("protocol", "TCP"), hp))
    return out


def node_allocatable(node: Mapping) -> dict[str, int]:
    return parse_resource_list(node.get("status", {}).get("allocatable"))


def node_is_unschedulable(node: Mapping) -> bool:
    return bool(node.get("spec", {}).get("unschedulable"))


def pod_is_terminal(pod: Mapping) -> bool:
    return pod.get("status", {}).get("phase") in ("Succeeded", "Failed")


def pod_priority(pod: Mapping) -> int:
    return pod.get("spec", {}).get("priority") or 0


# ---------------------------------------------------------------------------
# Taints & tolerations (pkg/apis/core/v1/helper + component-helpers
# scheduling/corev1/nodeaffinity; plugin: tainttoleration)
# ---------------------------------------------------------------------------

TAINT_NO_SCHEDULE = "NoSchedule"
TAINT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_NO_EXECUTE = "NoExecute"

TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"
TAINT_NOT_READY = "node.kubernetes.io/not-ready"


def toleration_tolerates_taint(tol: Mapping, taint: Mapping) -> bool:
    """v1helper.TolerationsTolerateTaint single-pair check.

    operator Exists (empty key ⇒ tolerate everything) or Equal (default);
    empty effect tolerates all effects.
    """
    if tol.get("effect") and tol["effect"] != taint.get("effect"):
        return False
    op = tol.get("operator", "Equal")
    if op == "Exists":
        return not tol.get("key") or tol["key"] == taint.get("key")
    return tol.get("key") == taint.get("key") and tol.get("value", "") == taint.get("value", "")


def find_untolerated_taint(
    taints: list, tolerations: list, effects: tuple[str, ...]
) -> Mapping | None:
    """First taint with effect in `effects` not tolerated by any toleration
    (v1helper.FindMatchingUntoleratedTaint)."""
    for taint in taints or []:
        if taint.get("effect") not in effects:
            continue
        if not any(toleration_tolerates_taint(t, taint) for t in tolerations or []):
            return taint
    return None
