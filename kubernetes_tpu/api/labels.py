"""Label and field selectors.

Capability parity with the reference's label machinery
(staging/src/k8s.io/apimachinery/pkg/labels/selector.go: `Parse`, `Selector.Matches`;
pkg/apis/meta/v1 `LabelSelector` with matchLabels + matchExpressions operators
In/NotIn/Exists/DoesNotExist; node-affinity adds Gt/Lt in
pkg/apis/core/v1/nodeaffinity).

Two consumers with different shapes:
- Control-plane paths (LIST filtering, controllers) match one object at a time —
  the functions here.
- The TPU scheduler needs *dense* matching over thousands of pods/nodes — that
  lives in kubernetes_tpu/ops/labelsets.py, which interns (key,value) pairs into
  integer ids and compiles a selector into index sets evaluated as tensor ops.
  The two must agree; tests/test_labelsets.py cross-checks them.
"""

from __future__ import annotations

import re
from typing import Iterable, Mapping


class Requirement:
    """One selector term: key op values."""

    __slots__ = ("key", "op", "values")

    def __init__(self, key: str, op: str, values: Iterable[str] = ()):
        self.key = key
        self.op = op  # In | NotIn | Exists | DoesNotExist | Gt | Lt
        self.values = list(values)

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        if self.op == "Exists":
            return has
        if self.op == "DoesNotExist":
            return not has
        if self.op == "In":
            return has and labels[self.key] in self.values
        if self.op == "NotIn":
            # Reference semantics (labels.Requirement.Matches): NotIn matches
            # when the key is absent OR the value is not in the set.
            return (not has) or labels[self.key] not in self.values
        if self.op in ("Gt", "Lt"):
            if not has:
                return False
            try:
                v = int(labels[self.key])
                bound = int(self.values[0])
            except (ValueError, IndexError):
                return False
            return v > bound if self.op == "Gt" else v < bound
        raise ValueError(f"unknown selector operator {self.op!r}")

    def __repr__(self) -> str:
        return f"Requirement({self.key} {self.op} {self.values})"


class Selector:
    """Conjunction of requirements. Empty selector matches everything."""

    __slots__ = ("requirements",)

    def __init__(self, requirements: Iterable[Requirement] = ()):
        self.requirements = list(requirements)

    def matches(self, labels: Mapping[str, str] | None) -> bool:
        labels = labels or {}
        return all(r.matches(labels) for r in self.requirements)

    def __repr__(self) -> str:
        return f"Selector({self.requirements})"


def from_label_selector(sel: Mapping | None) -> Selector:
    """Compile a meta/v1 LabelSelector dict → Selector.

    A nil LabelSelector matches nothing in the reference's
    metav1.LabelSelectorAsSelector only for *nil*; empty ({}) matches everything.
    Callers that need match-nothing-on-nil handle it themselves (we return a
    match-all for None for symmetry with labels.Everything(); workload
    controllers guard for nil explicitly).
    """
    if sel is None:
        return Selector()
    reqs: list[Requirement] = []
    for k, v in (sel.get("matchLabels") or {}).items():
        reqs.append(Requirement(k, "In", [v]))
    for expr in sel.get("matchExpressions") or []:
        reqs.append(Requirement(expr["key"], expr["operator"], expr.get("values") or []))
    return Selector(reqs)


def match_label_selector(sel: Mapping | None, labels: Mapping[str, str] | None) -> bool:
    return from_label_selector(sel).matches(labels)


#: Sentinel namespace set: "every namespace". An empty namespaceSelector
#: ({}) selects all namespaces in the reference (it matches any label set,
#: including namespaces with no labels or no Namespace object at all), so
#: resolution returns this instead of enumerating a namespace universe.
#: "*" cannot collide with a real namespace (DNS-1123 forbids it).
ALL_NAMESPACES = ("*",)


def ns_contains(namespaces, ns: str) -> bool:
    """Membership in a resolved namespace set, honoring ALL_NAMESPACES."""
    return "*" in namespaces or ns in namespaces


def is_empty_label_selector(sel: Mapping | None) -> bool:
    """True for the match-everything selector ({} or requirement-less)."""
    return sel is not None and not sel.get("matchLabels") \
        and not sel.get("matchExpressions")


def parse_selector(s: str) -> Selector:
    """Parse the string selector grammar: "a=b,c!=d,e in (x,y),f,!g".

    Mirrors labels.Parse (staging/src/k8s.io/apimachinery/pkg/labels/selector.go)
    for the common forms used by kubectl and field selectors.
    """
    reqs: list[Requirement] = []
    if not s.strip():
        return Selector()
    # Split on commas not inside parens.
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))

    for part in parts:
        part = part.strip()
        if not part:
            continue
        if part.startswith("!"):
            reqs.append(Requirement(part[1:].strip(), "DoesNotExist"))
            continue
        m = re.match(r"^([\w./-]+)\s+(in|notin)\s+\(([^)]*)\)$", part)
        if m:
            values = [v.strip() for v in m.group(3).split(",") if v.strip()]
            reqs.append(Requirement(m.group(1), "In" if m.group(2) == "in" else "NotIn", values))
            continue
        m = re.match(r"^([\w./-]+)\s*(==|!=|=)\s*([\w./-]*)$", part)
        if m:
            op = "NotIn" if m.group(2) == "!=" else "In"
            reqs.append(Requirement(m.group(1), op, [m.group(3)]))
            continue
        m = re.match(r"^([\w./-]+)$", part)
        if m:
            reqs.append(Requirement(m.group(1), "Exists"))
            continue
        raise ValueError(f"cannot parse selector clause {part!r}")
    return Selector(reqs)


def parse_field_selector(s: str) -> dict[str, str]:
    """Parse the `fieldSelector` query grammar: "spec.nodeName=n0,
    status.phase=Running" (fields.ParseSelector — the apiserver supports
    only exact-match terms, which is also what the store's tracked-field
    index serves)."""
    fields: dict[str, str] = {}
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        m = re.match(r"^([\w./-]+)\s*==?\s*([^,]*)$", part)
        if m is None:
            raise ValueError(f"cannot parse field selector clause {part!r}")
        fields[m.group(1)] = m.group(2).strip()
    return fields


def field_selector_to_string(fields: Mapping[str, str] | None) -> str:
    """Serialize a field map back to the query grammar."""
    if not fields:
        return ""
    return ",".join(f"{k}={v}" for k, v in sorted(fields.items()))


def selector_to_string(sel: Selector | None) -> str:
    """Serialize a Selector back to the string grammar parse_selector reads
    (the `labelSelector` query-parameter wire form)."""
    if sel is None or not sel.requirements:
        return ""
    parts: list[str] = []
    for r in sel.requirements:
        if r.op == "Exists":
            parts.append(r.key)
        elif r.op == "DoesNotExist":
            parts.append("!" + r.key)
        elif r.op == "In" and len(r.values) == 1:
            parts.append(f"{r.key}={r.values[0]}")
        elif r.op == "NotIn" and len(r.values) == 1:
            parts.append(f"{r.key}!={r.values[0]}")
        elif r.op in ("In", "NotIn"):
            parts.append(
                f"{r.key} {'in' if r.op == 'In' else 'notin'} "
                f"({','.join(r.values)})")
        else:
            raise ValueError(
                f"operator {r.op!r} has no string-selector form")
    return ",".join(parts)


def match_node_selector_terms(
    terms: list | None,
    node_labels: Mapping[str, str],
    node_name: str = "",
) -> bool:
    """RequiredDuringScheduling nodeSelectorTerms: OR of terms, AND within a term.

    Mirrors component-helpers' nodeaffinity.GetRequiredNodeAffinity /
    MatchNodeSelectorTerms semantics: empty/nil term list matches nothing here
    (callers treat absent affinity as match-all before calling). `node_name`
    backs matchFields on metadata.name — the only field selector the reference
    supports there.
    """
    if not terms:
        return False
    for term in terms:
        ok = True
        for expr in term.get("matchExpressions") or []:
            r = Requirement(expr["key"], expr["operator"], expr.get("values") or [])
            if not r.matches(node_labels):
                ok = False
                break
        if ok:
            for expr in term.get("matchFields") or []:
                if expr["key"] != "metadata.name":
                    ok = False
                    break
                r = Requirement("name", expr["operator"], expr.get("values") or [])
                ok = r.matches({"name": node_name})
                if not ok:
                    break
        if ok:
            return True
    return False
