"""Resident device planes: the packed used-state stays warm across cycles.

Pre-serving, every `assign()` re-uploaded the whole (N, 2R+1) int32
used-state pack (used_q ‖ used_nz_q ‖ used_pods) from the snapshot —
~1.4 MB per call at 50k nodes, paid even when one pod moved one node.
The host side stopped doing the equivalent in r13 (`SchedulerCache`
dirty-set snapshots + `ClusterTensors._init_delta` re-quantize only
changed rows); this class makes the device side match: the dirty row
set comes straight from the cache's `changed_since` log (O(changed),
never an O(N) generation walk), the rows are re-quantized from the
ClusterTensors arrays, and only they ship to the device — as a fused
scatter inside the fast-path solve (`solver.solve_one_fresh`, one
dispatch) or a standalone scatter for batch assigns
(`parallel/sharded.resident_row_scatter`).

Refresh contract (what invalidates what — README "Online serving path"):

- **row refresh**: a node's generation moved (assume/confirm/forget,
  informer node update) → that row is re-quantized and scattered.
  Bit-identical to a full upload by construction: both read the same
  ct rows.
- **full rebuild**: the node SET changed (`set_epoch`), the resource
  columns/scales/pad changed, the snapshot carries no epoch handles /
  the changed-log window doesn't reach back (fallback: one O(N) diff),
  or the dirty set exceeds REBUILD_FRACTION of the rows (a contiguous
  upload beats a dense scatter).
- a batch solve's on-device chained state (`backend._dev_used` after
  chunks ran) never touches the resident base — jax arrays are
  immutable and the next refresh re-derives from the cache, where the
  assumes landed anyway.

The host mirror (`_pack_np` + per-row generations) is updated at
refresh() time; the DEVICE array catches up when the caller applies
the returned delta (used_pack does it inline; the fast path fuses it
into the solve and `adopt()`s the result). Un-adopted deltas persist
in `_pending` and ride the next refresh — an exception between refresh
and adopt can delay a row, never lose it.

Block-index aggregates (the two-level node index, ops/solver
`block_bound_prefilter`): the same dirty-row set additionally maintains
per-block capacity interval planes — (amin_pos, amin, amax) over
allocatable and (umin, umax) over scoring-used, each (B, R) int32 with
B = ceil(n_real / block_w) — recomputed O(changed blocks · block_w)
per refresh and mirrored to device as one packed (B, 5R) upload
(`solver_block_refresh_seconds` records the wall). These planes are
OBSERVABILITY + serving-side reuse state: the fused batch solve
deliberately derives its block aggregates IN-PROGRAM from the live
`used_pack` instead of consuming them — a mid-batch verify-reject folds
used-state back DOWN, which would turn any maintained max/min stale in
the unsafe direction, while the O(changed) maintenance here is exact at
refresh boundaries (the parity test pins it against a from-scratch
recompute).
"""

from __future__ import annotations

import time

import numpy as np

from kubernetes_tpu.utils import flags

REBUILD_FRACTION = 0.25

#: masked-out sentinel for block minima — mirrors ops/kernels._BLOCK_BIG
#: so host-maintained planes equal the device kernels' bit-for-bit.
_BLOCK_BIG = 2 ** 30


class ResidentPlanes:
    def __init__(self, backend, metrics=None):
        self.backend = backend
        self.metrics = metrics
        self._key: tuple | None = None
        self._gen = -1
        self._gens: list | None = None
        self._pack_np: np.ndarray | None = None
        self._dev = None
        #: dirty rows whose device scatter hasn't been applied yet.
        self._pending: set[int] = set()
        #: observability (also mirrored into the metrics registry).
        self.full_rebuilds = 0
        self.row_refreshes = 0
        #: block-index aggregate planes (see module docstring): host
        #: dict of five (B, R) int32 planes + one packed device mirror.
        self._blocks: dict[str, np.ndarray] | None = None
        self._blocks_dev = None
        self._block_w = 0
        self._alloc_q: np.ndarray | None = None

    def invalidate(self) -> None:
        self._key = None
        self._dev = None
        self._pending.clear()
        self._blocks = None
        self._blocks_dev = None
        self._alloc_q = None

    # -- refresh ------------------------------------------------------------

    def _rebuild(self, ct) -> None:
        pack = np.concatenate(
            [ct.used_q, ct.used_nz_q,
             ct.used_pods.astype(np.int32)[:, None]], axis=1)
        self._pack_np = pack
        self._dev = self.backend._put(pack, "nodes_mat")
        self._gens = list(ct.node_gens)
        self._gen = ct.generation
        self._pending.clear()
        self.full_rebuilds += 1
        self._rebuild_blocks(ct)

    def refresh(self, ct, snapshot=None):
        """Bring the host mirror up to `ct` and return the device delta:
        None when the device array is already fresh (full rebuild, or
        nothing changed), else bucket-padded (rows, vals) the caller
        must apply — via used_pack's inline scatter or the fast path's
        fused solve followed by adopt()."""
        t0 = time.perf_counter()
        key = (ct.set_epoch, ct.n_pad, ct.n_real,
               tuple(ct.resources), tuple(ct.scales))
        out = None
        worked = False
        if self._dev is None or self._key != key or ct.set_epoch < 0:
            self._rebuild(ct)
            self._key = key
            worked = True
        else:
            changed = None
            fn = getattr(snapshot, "changed_since", None) \
                if snapshot is not None else None
            if fn is not None and self._gen >= 0:
                changed = fn(self._gen)
            if changed is None:
                # No changed-log window: one O(N) diff against the
                # mirror's per-row generations.
                changed = [i for i, g in enumerate(ct.node_gens)
                           if self._gens[i] != g]
            if len(changed) + len(self._pending) \
                    > REBUILD_FRACTION * max(ct.n_real, 1):
                self._rebuild(ct)
                worked = True
            else:
                self._gen = ct.generation
                fresh = [i for i in changed
                         if i < ct.n_real and self._gens[i]
                         != ct.node_gens[i]]
                for i in fresh:
                    self._gens[i] = ct.node_gens[i]
                self._pending.update(fresh)
                if self._pending:
                    idxs = np.fromiter(sorted(self._pending), np.int32,
                                       count=len(self._pending))
                    vals = np.concatenate(
                        [ct.used_q[idxs], ct.used_nz_q[idxs],
                         ct.used_pods[idxs].astype(np.int32)[:, None]],
                        axis=1)
                    self._pack_np[idxs] = vals
                    self.row_refreshes += 1
                    out = self._pad_bucket(idxs, vals)
                    self._refresh_blocks(ct, idxs)
                    worked = True
        if worked and self.metrics is not None:
            # No-op refreshes (nothing dirty) deliberately don't count:
            # the counter/histogram describe actual rebuild/scatter
            # work, and diluting them with no-op walls would misstate
            # the refresh cost the detail JSON reports.
            self.metrics.resident_plane_refreshes.inc()
            self.metrics.resident_plane_refresh.observe(
                time.perf_counter() - t0)
        return out

    @staticmethod
    def _pad_bucket(rows: np.ndarray, vals: np.ndarray):
        """Pad the delta to a power-of-two bucket (repeating the first
        row — the duplicate set is idempotent) so the jitted scatter /
        fused solve compiles once per bucket, not per dirty-set size."""
        cap = 1
        while cap < len(rows):
            cap <<= 1
        if cap > len(rows):
            pad = cap - len(rows)
            rows = np.concatenate(
                [rows, np.full((pad,), rows[0], np.int32)])
            vals = np.concatenate(
                [vals, np.repeat(vals[:1], pad, axis=0)])
        return rows, vals

    def adopt(self, dev) -> None:
        """Install a device pack that already includes every pending
        row (the fused fast-path solve returns it)."""
        self._dev = dev
        self._pending.clear()

    def apply_delta(self, delta) -> None:
        """Apply a refresh() delta via the standalone scatter (a tiny
        program — per-bucket compiles are cheap, unlike the fused
        solve's) and adopt the result."""
        from kubernetes_tpu.parallel.sharded import resident_row_scatter
        fn = resident_row_scatter(
            self.backend.mesh,
            getattr(self.backend, "_sh_nodes_mat", None))
        self.adopt(fn(self._dev, delta[0], delta[1]))

    def used_pack(self, ct, snapshot=None):
        """The refreshed device pack (the batch path's entry point):
        refresh, apply any delta via the standalone scatter, return."""
        delta = self.refresh(ct, snapshot)
        if delta is not None:
            self.apply_delta(delta)
        return self._dev

    # -- block-index aggregates ---------------------------------------------

    @staticmethod
    def _block_width_from_flags() -> int:
        """Resolve the maintained block width from the flag registry:
        0 (index off) under the KTPU_BLOCK_INDEX kill switch, else the
        KTPU_BLOCK_WIDTH override, else the tuner's default width."""
        if not flags.get("KTPU_BLOCK_INDEX"):
            return 0
        override = flags.get("KTPU_BLOCK_WIDTH")
        if override is not None:
            return max(0, int(override))
        from kubernetes_tpu.ops.backend import AdaptiveTuner
        return AdaptiveTuner.BLOCK_WIDTH

    def _rebuild_blocks(self, ct) -> None:
        """Full recompute of the five (B, R) planes over the real rows.

        Called from _rebuild (the node set / columns / pad changed, so
        every block is dirty anyway). Sentinels match ops/kernels
        .block_capacity_aggregates: minima fill with _BLOCK_BIG, maxima
        with 0, and amin_pos additionally masks zero-alloc columns —
        the device kernel folds the same values in the same dtype, so
        the parity test can compare bit-for-bit.
        """
        bw = self._block_w = self._block_width_from_flags()
        if not bw:
            self._blocks = None
            self._blocks_dev = None
            self._alloc_q = None
            return
        n = ct.n_real
        alloc = np.asarray(ct.alloc_q[:n], dtype=np.int32)
        self._alloc_q = alloc.copy()
        r = alloc.shape[1]
        used_nz = self._pack_np[:n, r:2 * r]
        b = -(-n // bw) if n else 0

        def fold(x, fill):
            pad = b * bw - n
            if pad:
                x = np.concatenate(
                    [x, np.full((pad, r), fill, np.int32)])
            return x.reshape(b, bw, r)

        self._blocks = {
            "amin_pos": fold(np.where(alloc > 0, alloc, _BLOCK_BIG),
                             _BLOCK_BIG).min(axis=1),
            "amin": fold(alloc, _BLOCK_BIG).min(axis=1),
            "amax": fold(alloc, 0).max(axis=1),
            "umin": fold(used_nz, _BLOCK_BIG).min(axis=1),
            "umax": fold(used_nz, 0).max(axis=1),
        } if b else {
            k: np.zeros((0, r), np.int32)
            for k in ("amin_pos", "amin", "amax", "umin", "umax")
        }
        self._upload_blocks()

    def _refresh_blocks(self, ct, idxs: np.ndarray) -> None:
        """Recompute only the blocks containing dirty rows — the
        O(changed blocks · block_w) path the module docstring promises.
        `idxs` are the already-filtered real dirty rows (< n_real)."""
        if self._blocks is None or self._block_w <= 0:
            return
        t0 = time.perf_counter()
        bw = self._block_w
        n = self._alloc_q.shape[0]
        r = self._alloc_q.shape[1]
        # allocatable can move too (informer node updates ride the same
        # dirty set) — re-snapshot those rows before aggregating.
        self._alloc_q[idxs] = np.asarray(ct.alloc_q[idxs], dtype=np.int32)
        for blk in np.unique(idxs // bw):
            lo, hi = int(blk) * bw, min((int(blk) + 1) * bw, n)
            alloc = self._alloc_q[lo:hi]
            used_nz = self._pack_np[lo:hi, r:2 * r]
            self._blocks["amin_pos"][blk] = np.where(
                alloc > 0, alloc, _BLOCK_BIG).min(axis=0)
            self._blocks["amin"][blk] = alloc.min(axis=0)
            self._blocks["amax"][blk] = alloc.max(axis=0)
            self._blocks["umin"][blk] = used_nz.min(axis=0)
            self._blocks["umax"][blk] = used_nz.max(axis=0)
        self._upload_blocks()
        if self.metrics is not None:
            self.metrics.solver_block_refresh.observe(
                time.perf_counter() - t0)

    def _upload_blocks(self) -> None:
        """Mirror the host planes to device as one packed (B, 5R)
        upload (small: ~20 B/block·resource, one transfer per refresh)."""
        self._blocks_dev = self.backend._put(np.concatenate(
            [self._blocks[k] for k in
             ("amin_pos", "amin", "amax", "umin", "umax")],
            axis=1).astype(np.int32))

    # -- test/debug hooks ---------------------------------------------------

    def host_mirror(self) -> np.ndarray | None:
        """The host copy of the resident pack (None before first use)."""
        return self._pack_np

    def block_aggregates(self):
        """(block_w, host planes dict, packed device mirror) — None
        planes when the block index is off. The parity test recomputes
        the planes from scratch off the host mirror and compares."""
        return self._block_w, self._blocks, self._blocks_dev
