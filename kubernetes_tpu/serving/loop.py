"""ServingTier: the admission window + fast path wired into the
scheduler's dispatch loop.

`Scheduler.run` delegates each iteration to `schedule_next` when the
tier is attached (serving/__init__.maybe_attach_serving — flagless,
KTPU_SERVING=0 kill switch):

    pop_batch ──▶ admission window (dispatch now / coalesce) ──▶
        dispatch ≤ fast-path cap ──▶ drain pod-by-pod through the
            pinned C=1 solve (resident planes, solve_one); the first
            ineligible / no-fit pod and everything behind it falls to ─┐
        dispatch > cap ────────────▶ Scheduler._schedule_pods ◀───────┘
                                     (the unchanged batch pipeline)

Why a CAP and not "len == 1": a chunk's wall is fixed (the scan runs
the padded width — ~0.35 s at 5k on the CPU container) while a fast
solve is ~1–2 ms, so BELOW chunk/fast pods the serial drain is faster
outright — and, more importantly, it keeps the queue in the lone-pod
regime. The r15 trickle pathology was self-sustaining: arrivals
accumulating during one chunk wall guaranteed the next pop was another
chunk, so 250/s traffic ran batch-every-0.4s forever. Draining small
dispatches serially converges back to empty-queue/lone-pod steady
state; genuine bursts blow past the cap and get the batch pipeline.
Both walls are measured EWMAs fed from the tier's own dispatches
(AdaptiveTuner.fast_path_cap is the pure-policy row; seeds cover the
pre-measurement window, and the first fast sample — the jit compile —
is excluded). The fast-path program itself is pre-compiled during the
first BATCH dispatch the tier sees (one discarded solve), so a
measured serve window never pays the compile.

The drain preserves queue (priority) order exactly: pods ahead of the
first fall-through pod place first, the remainder dispatches as one
batch in order. Everything below the dispatch decision — assume,
Reserve, Permit, the async binding cycle, failure handling, preemption
— is the scheduler's existing machinery, untouched.
"""

from __future__ import annotations

import asyncio
import logging
import statistics
import time
from collections import deque

from kubernetes_tpu.ops.backend import AdaptiveTuner
from kubernetes_tpu.scheduler.framework import CycleState
from kubernetes_tpu.serving.admission import AdmissionWindow
from kubernetes_tpu.serving.fastpath import SinglePodFastPath
from kubernetes_tpu.serving.resident import ResidentPlanes
from kubernetes_tpu.utils.tracing import traceparent_of

logger = logging.getLogger(__name__)

#: window of recent wall samples per estimator: the MEDIAN is the
#: estimate, so a jit-compile outlier (a novel input bucket, a fresh
#: cluster shape) cannot crater the fast-path cap the way an EWMA
#: poisoned by one 100 ms compile did — that spiral locked the tier
#: into the batch regime for the rest of a serve window.
_WALL_WINDOW = 15


class ServingTier:
    def __init__(self, sched):
        self.sched = sched
        backend = sched.backend
        self.window = AdmissionWindow(
            tuner=getattr(backend, "_tuner", None), metrics=sched.metrics)
        self.resident = ResidentPlanes(backend, metrics=sched.metrics)
        self.fastpath = SinglePodFastPath(
            backend, self.resident, metrics=sched.metrics)
        # Batch assigns now seed their device chain from the resident
        # planes too (ops/backend._start).
        backend.resident = self.resident
        #: recent wall samples; the medians feed the cap policy row
        #: (0.0 = unmeasured, the policy row's seeds apply).
        self._fast_walls: deque = deque(maxlen=_WALL_WINDOW)
        self._chunk_walls: deque = deque(maxlen=_WALL_WINDOW)
        self._fast_samples = 0
        self._last_fast_t = 0.0

    #: fast-wall samples older than this with nothing newer are dropped:
    #: a couple of outlier samples in a near-empty window (a mid-serve
    #: compile that slipped past warmup) would otherwise suppress the
    #: fast path forever — suppression itself prevents the fresh samples
    #: that would heal the median. Decay turns it into a bounded retry.
    _FAST_WALL_STALE_S = 10.0

    @property
    def fast_wall_est(self) -> float:
        if not self._fast_walls:
            return 0.0
        if time.monotonic() - self._last_fast_t > self._FAST_WALL_STALE_S:
            self._fast_walls.clear()
            return 0.0
        return statistics.median(self._fast_walls)

    @property
    def chunk_wall_est(self) -> float:
        return statistics.median(self._chunk_walls) if self._chunk_walls \
            else 0.0

    def fast_path_cap(self) -> int:
        return AdaptiveTuner.fast_path_cap(
            self.chunk_wall_est, self.fast_wall_est,
            n_nodes=len(self.sched.cache.nodes))

    async def schedule_next(self, batch_size: int) -> bool:
        """One dispatch-loop iteration. Returns False when the queue
        closed (mirrors Scheduler.schedule_batch's contract)."""
        sched = self.sched
        pods = await sched.queue.pop_batch(batch_size)
        if not pods:
            return False
        self.window.observe_pop(len(pods))
        # Coalescing reads POPPABLE backlog only (activeQ): in-flight
        # pods can never fill the next pop, and counting them disabled
        # coalescing in exactly the above-trickle regime it serves.
        wait = self.window.window_for(
            len(pods), sched.queue.stats()["active"], batch_size)
        if wait > 0 and len(pods) < batch_size:
            # COALESCE: hold the queue open, then merge what arrived.
            await asyncio.sleep(wait)
            more = await sched.queue.pop_now(batch_size - len(pods))
            if more:
                pods.extend(more)
                # Merged pods count toward the offered-rate estimate
                # too — under heavy coalescing they're the majority,
                # and missing them would read the rate far low exactly
                # when the utilization gates need it accurate.
                self.window.observe_pop(len(more))
                sched.metrics.serving_coalesced_batches.inc()
        # Two routing signals, both measured: (a) total OUTSTANDING work
        # (this dispatch + everything still queued or in a cycle —
        # parked unschedulable/gated pods deliberately EXCLUDED: a
        # standing unschedulable set is not poppable work and must not
        # permanently disable the fast path) within the fast-path cap,
        # and (b) the estimated OFFERED rate within the serial drain's
        # capacity (utilization headroom) — a sustained drain through a
        # shared-loop wire self-throttles its own creates to the drain
        # rate, so backlog alone never reveals the pressure and serial
        # solves would silently become the throughput ceiling. Fail
        # either → the pipelined batch path.
        qs = sched.queue.stats()  # re-read: the coalesce merge moved it
        outstanding = qs["active"] + qs["in_flight"]
        if sched.backend is not None and not sched.extenders:
            if not self.fastpath.warmed:
                # Retried until a usable donor pod appears (a dispatch
                # may carry only ineligible shapes), WHATEVER branch
                # this dispatch takes — warming only on the batch
                # branch once left the fused variants cold, and their
                # mid-serve compiles poisoned the wall estimate.
                self._warm_fast_path(pods[0])
            if outstanding <= self.fast_path_cap() \
                    and self.window.rate_est \
                    <= AdaptiveTuner.fast_path_rate_limit(
                        self.fast_wall_est,
                        n_nodes=len(sched.cache.nodes)):
                pods = await self._drain_fast(pods)
                if not pods:
                    return True
        await self._schedule_batch_timed(pods)
        return True

    # -- the fast drain -----------------------------------------------------

    #: mid-drain pressure check cadence (pods).
    _DRAIN_CHECK_EVERY = 4
    #: fresh arrivals waiting in activeQ that mean a burst is landing
    #: NOW: a kept-up serial drain leaves active in the low single
    #: digits (arrivals per fast solve = rate × fast_wall < 1 inside
    #: the rate limit), so tens of queued pods mid-drain can only be a
    #: burst/drain onset — abort to the batch path within ~4 pods.
    _DRAIN_ABORT_ACTIVE = 32

    async def _drain_fast(self, pods: list) -> list:
        """Place the eligible PREFIX of a small dispatch pod-by-pod
        through the fast path; returns the remainder (first ineligible /
        no-fit pod onward, order preserved) for the batch pipeline.

        Every few pods the drain re-checks queue pressure: when fresh
        arrivals landing DURING the serial drain exceed the abort
        threshold (or push remaining+queued past the cap), it aborts to
        the batch path — the entry gates can't see a burst that starts
        cold (the two-point rate estimate reads 0 until a second pop
        exists), but the burst betrays itself here within a few pods."""
        cap = self.fast_path_cap()
        stats = self.sched.queue.stats
        for k, pi in enumerate(pods):
            if k and k % self._DRAIN_CHECK_EVERY == 0:
                active = stats()["active"]
                if active > self._DRAIN_ABORT_ACTIVE \
                        or len(pods) - k + active > cap:
                    return pods[k:]
            if not await self._try_fast_path(pi):
                return pods[k:]
        return []

    async def _try_fast_path(self, pi) -> bool:
        sched = self.sched
        if sched.backend is None or pi.nominated_node:
            return False
        fwk = sched.profiles.get(pi.scheduler_name)
        if fwk is None:
            return False
        if sched.backend_profiles is not None \
                and pi.scheduler_name not in sched.backend_profiles:
            return False
        # Zero-copy snapshot: consumed synchronously inside this cycle
        # (ct build → eligibility → solve → verify), dropped before the
        # assume mutates the cache — the light contract.
        snapshot = sched.cache.light_snapshot()
        if sched.tracer.enabled:
            with sched.tracer.span(
                    "scheduler.attempt", pod=pi.key,
                    profile=fwk.profile_name, fast_path=True,
                    traceparent=traceparent_of(pi.pod)):
                sched._record_queue_wait(pi)
                return await self._fast_cycle(pi, snapshot, fwk)
        return await self._fast_cycle(pi, snapshot, fwk)

    async def _fast_cycle(self, pi, snapshot, fwk) -> bool:
        sched = self.sched
        t0 = time.perf_counter()
        try:
            node = self.fastpath.try_schedule(pi, snapshot, fwk)
        except Exception:
            # The fast path must never break scheduling: any device/host
            # error just reroutes the pod through the normal path (and
            # does NOT count toward the batch backend's circuit breaker
            # — a fast-path-only fault shouldn't kill batch solves).
            logger.exception("fast path failed for %s; normal path", pi.key)
            return False
        wall = time.perf_counter() - t0
        if node is None:
            return False
        self._fast_samples += 1
        if self._fast_samples > 1:
            # The first sample carries the jit compile when warmup was
            # skipped — policy seeds cover until a warm sample lands.
            self._fast_walls.append(wall)
            self._last_fast_t = time.monotonic()
        sched.metrics.observe_attempt("scheduled", fwk.profile_name, wall)
        await sched._assume_and_bind(fwk, CycleState(), pi, node)
        return True

    # -- batch side ---------------------------------------------------------

    async def _schedule_batch_timed(self, pods: list) -> None:
        """The unchanged batch pipeline, with the per-chunk solve wall
        sampled off scheduler_tpu_solve_seconds for the cap policy."""
        sched = self.sched
        h = sched.metrics.solve_duration
        c0, s0 = h.count(), h.sum()
        await sched._schedule_pods(pods)
        dc = h.count() - c0
        if dc > 0:
            self._chunk_walls.append((h.sum() - s0) / dc)

    def _warm_fast_path(self, pi) -> None:
        """Compile every fast-path program variant OFF the serve path
        (one discarded solve + both fused refresh buckets) — nothing
        assumed, nothing counted, and no measured lone-pod placement
        ever pays a jit. Retried (cheaply) until fastpath.warmed flips;
        a no-fit donor works, only ineligible shapes are skipped."""
        sched = self.sched
        fwk = sched.profiles.get(pi.scheduler_name)
        if fwk is None or pi.nominated_node:
            return
        try:
            self.fastpath.warm(pi, sched.cache.update_snapshot(), fwk)
        except Exception:  # pragma: no cover - warmup is best-effort
            logger.debug("fast-path warmup failed", exc_info=True)
