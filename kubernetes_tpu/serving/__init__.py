"""The online serving tier: single-pod latency as a first-class path.

ROADMAP #3 (seeded by the r15 churn knee data): the batch pipeline is
worst at production's most common shape — a trickle of lone pods that
each want sub-millisecond placement. BASELINE r15 measured the 5k-node
knee at 1000/s with attempt p999 41.8 ms, while the 250/s trickle row
was 190.7 ms p999 / 3.8 ms p50: every lone pod paid a full per-pod host
scan (the batched backend only engages above one pod) with nothing to
amortize it. This package wins the `ScheduleOne` latency shape back
(SURVEY §3.1) without touching the batch headline, via three
cooperating layers:

- **Adaptive admission window** (admission.py): in front of the
  scheduler's `pop_batch` loop — dispatch immediately when arrivals are
  a trickle, hold the queue open for a few ms to coalesce a real batch
  under backlog. Thresholds ride the AdaptiveTuner's policy row
  (ops/backend.AdaptiveTuner.admission_window), seeded from the r15
  knee sweep; `KTPU_ADMISSION_WINDOW` (ms) / bench `--admission-window`
  override.
- **Resident device planes** (resident.py): the (N, 2R+1) packed
  used-state stays warm on device across cycles and is refreshed by
  scattering only the rows the cache's dirty set re-quantized
  (`changed_since` — the r13 O(changed) host prep, now matched on the
  device side) instead of a full re-upload per assign().
- **Pinned single-pod fast path** (fastpath.py + ops/solver.solve_one):
  a pre-compiled fixed-shape C=1 solve against the resident planes —
  gather → mask → score → argmax → debit, no chunk machinery, no tuner,
  no shortlist build — bit-identical to the batch path by construction
  (it composes the same kernels the fused chunk program does).

`KTPU_SERVING=0` is the kill switch: the scheduler's run loop degrades
STRUCTURALLY to the pre-serving shape (plain schedule_batch, full
used-state uploads, lone pods on the host path).
"""

from __future__ import annotations

from kubernetes_tpu.serving.admission import AdmissionWindow
from kubernetes_tpu.serving.fastpath import SinglePodFastPath
from kubernetes_tpu.serving.loop import ServingTier
from kubernetes_tpu.serving.resident import ResidentPlanes
from kubernetes_tpu.utils import flags

__all__ = [
    "AdmissionWindow",
    "ResidentPlanes",
    "ServingTier",
    "SinglePodFastPath",
    "serving_enabled",
    "maybe_attach_serving",
]


def serving_enabled() -> bool:
    """KTPU_SERVING kill switch; default ON (the serving tier is the
    flagless production shape, like the class planes and the shortlist)."""
    return flags.get("KTPU_SERVING")


def maybe_attach_serving(sched) -> "ServingTier | None":
    """Build (once) and return the scheduler's serving tier, or None when
    the kill switch is set / no batched backend is attached. Called at
    run()-loop entry so tests can flip KTPU_SERVING between runs."""
    if not serving_enabled() or sched.backend is None:
        if sched.serving is not None:
            # Kill switch flipped between runs: detach so the backend's
            # _start returns to full used-state uploads.
            if sched.backend is not None:
                sched.backend.resident = None
            sched.serving = None
        return None
    if sched.serving is None:
        sched.serving = ServingTier(sched)
    return sched.serving
