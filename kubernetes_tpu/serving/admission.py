"""Adaptive admission window: dispatch-now vs coalesce, in front of
`SchedulingQueue.pop_batch`.

State machine (README "Online serving path" documents the contract):

    IDLE ──pop──▶ DISPATCH (window 0: lone pods → fast path, batches →
      ▲                     the batch pipeline immediately)
      │
    COALESCE: estimated offered rate is above the trickle threshold AND
      the pop returned fewer pods than the caller's batch budget — hold
      the queue open `window` seconds, then drain whatever accumulated
      (one merged dispatch), then DISPATCH.

The decision inputs are all measured, never configured (the AdaptiveTuner
discipline):

- **offered-rate estimate**: EWMA of pods-per-second observed at the pop
  boundary (the open-loop arrival process as the queue sees it).
- **pop size / backlog depth**: `pop_batch`'s return and
  `queue.backlog_depth()` — a pop that already filled the batch budget
  never waits; a deep backlog means the NEXT pop will fill it, so
  waiting adds latency for nothing.

The window length itself is the AdaptiveTuner policy row
(`AdaptiveTuner.admission_window` — thresholds seeded from the r15
churn knee sweep, BASELINE r15): 0 at or below the 250/s trickle, else
sized to coalesce ~8 pods at the estimated rate, capped at 4 ms (16 ms
when the device is relay-attached — each dispatch pays a
size-independent RTT there, so fuller batches win).

`KTPU_ADMISSION_WINDOW` (milliseconds) pins the window for sweeps and
tests; `0` disables coalescing entirely (every pop dispatches
immediately — the admission half of the KTPU_SERVING=0 degrade).
"""

from __future__ import annotations

import time

from kubernetes_tpu.ops.backend import AdaptiveTuner
from kubernetes_tpu.utils import flags


def _window_override_ms() -> float | None:
    return flags.get("KTPU_ADMISSION_WINDOW")


class AdmissionWindow:
    #: offered-rate estimation horizon: pods observed at pop boundaries
    #: over the last window, TWO-POINT form — rate = (pods after the
    #: oldest pop) / (time since the oldest pop). Per-pop instantaneous
    #: rates were hopeless: Poisson bunching at a 250/s trickle yields
    #: back-to-back pops whose inst rate reads thousands, and one such
    #: spike through an EWMA flipped the tier into a chunk excursion
    #: mid-trickle. The two-point estimate is exact for any steady
    #: process regardless of bunching; a window with fewer than two
    #: pops reads 0 (unknown — the mid-drain pressure abort owns the
    #: cold-burst case).
    RATE_WINDOW_S = 0.5

    def __init__(self, tuner: AdaptiveTuner | None = None, metrics=None):
        self.tuner = tuner
        self.metrics = metrics
        self.rate_est = 0.0
        from collections import deque
        self._pops: "deque[tuple[float, int]]" = deque()
        self._pop_sum = 0
        #: decisions, for introspection/tests.
        self.immediate_dispatches = 0
        self.coalesce_windows = 0

    def observe_pop(self, n_pods: int, now: float | None = None) -> None:
        """Feed one pop boundary into the rate estimate."""
        now = time.monotonic() if now is None else now
        self._pops.append((now, n_pods))
        self._pop_sum += n_pods
        while self._pops and self._pops[0][0] < now - self.RATE_WINDOW_S \
                and len(self._pops) > 2:
            _, n = self._pops.popleft()
            self._pop_sum -= n
        if len(self._pops) >= 2:
            t0, n0 = self._pops[0]
            span = now - t0
            self.rate_est = (self._pop_sum - n0) / span if span > 0 else 0.0
        else:
            self.rate_est = 0.0

    def window_for(self, popped: int, backlog: int,
                   batch_budget: int) -> float:
        """Seconds to hold the queue open before dispatching this pop
        (0.0 = dispatch immediately)."""
        override = _window_override_ms()
        if override is not None:
            w = override * 1e-3
        else:
            latency = 0.0
            if self.tuner is not None and self.tuner.latency_s is not None:
                latency = self.tuner.latency_s
            w = AdaptiveTuner.admission_window(latency, self.rate_est)
        if popped >= batch_budget or backlog >= batch_budget:
            # The batch budget is already met (or the next pop meets it):
            # waiting only adds latency.
            w = 0.0
        if self.metrics is not None:
            # Base-unit seconds (scheduler_admission_window_seconds) —
            # the old _ms gauge was the metrics lint's first real catch.
            self.metrics.admission_window.set(round(w, 6))
        if w > 0.0:
            self.coalesce_windows += 1
        else:
            self.immediate_dispatches += 1
        return w
