"""Pinned single-pod fast path: one pre-compiled C=1 solve per placement.

The batch pipeline's per-pod cost is amortization — chunk build, class
interning, shortlist prefilter, multistart permutations — none of which
a lone pod can use; pre-serving, the scheduler routed lone pods to the
per-pod HOST path instead (a full O(N·plugins) Python scan: the r15
trickle row's 3.8 ms p50). This path is the third shape: the pod's
equivalence-class row solves against the RESIDENT device planes through
`ops/solver.solve_one` — the exact kernel composition of the fused
chunk program's first scan step (same kernels, same order, same dtypes,
same argmax tie rule), so assignments are bit-identical to the batch
path by construction (tests/test_serving_smoke.py pins it with a
randomized differential).

Eligibility (README "Online serving path" documents the contract): a
pod takes the fast path only when every plugin influence on its
placement is representable in the resident planes —

- requests covered by the tracked resource columns;
- no nominated node (preemptor retries keep their nominee-first check);
- static-row plugins (NodeAffinity/NodeName/NodeUnschedulable) allowed:
  their signature-cached rows AND into the pod's base mask (a NodeName
  pin is just a one-column mask here — a lone pod's argmax over ≤1
  column cannot be moved by score normalization, so the batch path's
  exception-column form is assignment-identical);
- every stateful filter/score gate inactive (no affinity terms against
  a term-free cluster, no spread constraints, no ports/volumes/claims,
  no NRT/DRA activity) — the same `_FILTER_ACTIVE`/`_SCORE_ACTIVE`
  gates the chunk prep consults, so "gate says the plugin would Skip"
  means exactly what it means there;
- no nonzero host score rows (preferred node affinity, image locality
  against image-bearing nodes) — score normalization is feasible-set
  relative and belongs to the chunk prep;
- no gang membership (Coscheduling atomicity needs the batch solver).

Anything else falls through to the normal path (batch or host), which
also owns diagnostics/preemption for no-fit pods — the fast path only
takes the happy path, and a host verify (exact integer re-check)
backstops the quantized device fit exactly like the batch verify.
"""

from __future__ import annotations

import logging

import numpy as np

from kubernetes_tpu.ops import solver
from kubernetes_tpu.ops.backend import (
    DEVICE_FILTER_PLUGINS,
    DEVICE_SCORE_PLUGINS,
    STATIC_ROW_PLUGINS,
    STATIC_SCORE_PLUGINS,
    _FILTER_ACTIVE,
    _SCORE_ACTIVE,
)
from kubernetes_tpu.scheduler.plugins.noderesources import (
    insufficient_resources,
)
from kubernetes_tpu.utils.locking import check_dispatch_seam

logger = logging.getLogger(__name__)

#: Largest refresh delta the solve fuses (solve_one_fresh): each bucket
#: size is a separate jit signature of the FULL solve program, so only
#: the steady-state buckets stay fused — between consecutive lone-pod
#: placements exactly one node changes (the previous assume, plus its
#: bind confirmation on the same row), occasionally two. Bigger deltas
#: (the first solve after a batch dispatch dirtied a chunk's worth of
#: rows) apply through the standalone scatter — a tiny program whose
#: per-bucket compiles are cheap — and solve un-fused. Without this
#: split, every novel bucket recompiled the whole solve mid-serve and
#: the compile walls poisoned the tier's fast-wall estimate.
FUSE_MAX_ROWS = 2


class SinglePodFastPath:
    def __init__(self, backend, resident, metrics=None):
        self.backend = backend
        self.resident = resident
        self.metrics = metrics
        #: (taint-table id, scales, req/tol signature) -> packed
        #: (2R+tf+tp,) int32 class row (the solve_one req_pack).
        self._req_cache: dict[tuple, np.ndarray] = {}
        #: (row ids, n_pad) -> device bit-packed base mask; invalidated
        #: with the backend row cache (same static fingerprint).
        self._mask_cache: dict[tuple, object] = {}
        self._mask_fp: tuple | None = None
        #: resident all-true mask / zero score rows per plane shape.
        self._alltrue: dict[tuple, object] = {}
        self._zero_scores: dict[int, object] = {}
        #: introspection counters (the serving tier also mirrors the
        #: success count into the metrics registry).
        self.placed = 0
        self.ineligible = 0
        self.no_fit = 0
        #: every program variant compiled (warm() completed) — the
        #: serving tier retries warm-up until a usable donor pod
        #: appears, so this flips exactly once per cluster shape.
        self.warmed = False

    # -- eligibility --------------------------------------------------------

    def _base_rows(self, pi, snapshot, fwk, ct) -> list | None:
        """The pod's host filter rows (static plugins only), or None when
        any plugin outside the fast path's vocabulary is live for it."""
        rows = []
        for plugin in fwk.filter_plugins:
            name = plugin.NAME
            if name in DEVICE_FILTER_PLUGINS:
                continue
            if name in STATIC_ROW_PLUGINS:
                row, all_true = self.backend._static_filter_row(
                    plugin, pi, snapshot, ct)
                if not all_true:
                    rows.append(row)
                continue
            gate = _FILTER_ACTIVE.get(name)
            if gate is None or gate(plugin, pi, snapshot):
                return None
        for plugin in fwk.score_plugins:
            name = plugin.NAME
            if name in DEVICE_SCORE_PLUGINS:
                continue
            if name in STATIC_SCORE_PLUGINS:
                if name == "NodeAffinity":
                    if ((pi.affinity.get("nodeAffinity") or {}).get(
                            "preferredDuringSchedulingIgnoredDuringExecution")):
                        return None
                    continue
                _, any_nonzero = self.backend._static_score_row(
                    plugin, pi, snapshot, ct)
                if any_nonzero:
                    return None
                continue
            gate = _SCORE_ACTIVE.get(name)
            if gate is None or gate(plugin, pi, snapshot):
                return None
        cosched = next(
            (pl for pl in fwk.plugins if pl.NAME == "Coscheduling"), None)
        if cosched is not None and cosched.group_key(pi):
            return None
        return rows

    # -- device inputs ------------------------------------------------------

    def _req_pack(self, pi, ct):
        """DEVICE-cached (2R+tf+tp,) class row for the pod's request /
        toleration signature — template pods hit this every solve, so
        the upload happens once per signature, not per placement. The
        cache is cleared with the static fingerprint (in _base_mask):
        the taint table rebuilds exactly when the fingerprint moves, so
        no table identity belongs in the key (an id() there could match
        a recycled address and serve stale untolerated masks)."""
        key = (tuple(ct.scales), tuple(ct.resources),
               repr(pi.requests), repr(pi.nonzero_requests),
               repr(pi.tolerations))
        pack = self._req_cache.get(key)
        if pack is None:
            if len(self._req_cache) > 4096:
                self._req_cache.clear()
            q, qnz = ct.quantize_requests(pi.requests, pi.nonzero_requests)
            uf = ct.taints.untolerated(pi.tolerations, "filter")
            up = ct.taints.untolerated(pi.tolerations, "prefer")
            pack = self.backend._put(np.concatenate(
                [q, qnz, uf.astype(np.int32), up.astype(np.int32)]))
            self._req_cache[key] = pack
        return pack

    def _base_mask(self, rows, ct):
        """Device bit-packed base mask for the pod's host-row set: the
        resident all-true plane for the (overwhelmingly common) empty
        set, one cached upload per distinct row set otherwise."""
        if self._mask_fp != ct._static_fp:
            # Static fingerprint moved (cordon, taint edit, node churn):
            # the backend row cache just reset, and row identities with
            # it — the masks derived from them are stale too, as are
            # the req packs (their untolerated vectors were built
            # against the previous taint table).
            self._mask_cache.clear()
            self._alltrue.clear()
            self._req_cache.clear()
            self._mask_fp = ct._static_fp
        if not rows:
            key = (ct.n_pad, ct.n_real)
            dev = self._alltrue.get(key)
            if dev is None:
                m = np.zeros((ct.n_pad,), dtype=np.bool_)
                m[: ct.n_real] = True
                # Replicated: N/8 bytes — smaller than any sharding win.
                dev = self._alltrue[key] = self.backend._put(np.packbits(m))
            return dev
        key = tuple(id(r) for r in rows) + (ct.n_pad,)
        dev = self._mask_cache.get(key)
        if dev is None:
            if len(self._mask_cache) > 1024:
                self._mask_cache.clear()
            m = np.zeros((ct.n_pad,), dtype=np.bool_)
            m[: ct.n_real] = True
            for r in rows:
                m[: ct.n_real] &= r
            dev = self._mask_cache[key] = self.backend._put(np.packbits(m))
        return dev

    def _zero_score_row(self, ct):
        dev = self._zero_scores.get(ct.n_pad)
        if dev is None:
            # f16 like the batch wire's clean score plane (cast to f32 on
            # device in both paths — zeros are exact either way).
            dev = self._zero_scores[ct.n_pad] = self.backend._put(
                np.zeros((ct.n_pad,), dtype=np.float16), "nodes_vec")
        return dev

    # -- the solve ----------------------------------------------------------

    def try_schedule(self, pi, snapshot, fwk, record: bool = True) -> str | None:
        """One placement attempt. Returns the node name, or None when the
        pod is ineligible / nothing fits (the caller routes it through
        the normal path, which owns diagnostics and preemption).
        record=False is the warmup form: full solve, nothing counted
        (the caller discards the result without assuming)."""
        backend = self.backend
        ct = backend._tensors(snapshot)
        if pi.nominated_node or ct.has_unknown_resource(pi.requests):
            self.ineligible += 1
            return None
        rows = self._base_rows(pi, snapshot, fwk, ct)
        if rows is None:
            self.ineligible += 1
            return None
        params = backend._fwk_params(fwk, ct)
        static = backend.ensure_static(ct)
        tail = (self._base_mask(rows, ct), self._zero_score_row(ct),
                self._req_pack(pi, ct),
                params["fit_col_w"], params["bal_col_mask"],
                params["shape_u"], params["shape_s"],
                params["w_fit"], params["w_bal"], params["w_taint"],
                params["taint_filter_on"], params["strategy"])
        delta = self.resident.refresh(ct, snapshot)
        if delta is not None and len(delta[0]) > FUSE_MAX_ROWS:
            self.resident.apply_delta(delta)
            delta = None
        if delta is None:
            idx_d = solver.solve_one(
                static["alloc_q"], self.resident._dev,
                static["alloc_pods"], static["taint_f"],
                static["taint_p"], *tail)
        else:
            # Fused refresh+solve: one dispatch applies the dirty rows
            # and solves; the refreshed pack becomes the resident base.
            idx_d, pack = solver.solve_one_fresh(
                static["alloc_q"], self.resident._dev,
                delta[0], delta[1], static["alloc_pods"],
                static["taint_f"], static["taint_p"], *tail)
            self.resident.adopt(pack)
        check_dispatch_seam("serving.fastpath.fetch")
        idx = int(np.asarray(idx_d))
        if idx < 0 or idx >= ct.n_real:
            self.no_fit += 1
            return None
        name = ct.node_names[idx]
        ni = snapshot.get(name)
        if ni is None or insufficient_resources(pi, ni):
            # Quantized fit is conservative, so this is belt-and-braces:
            # route the pod through the exact batch verify instead.
            logger.warning(
                "fast path verify rejected %s on %s; rerouting", pi.key,
                name)
            self.no_fit += 1
            return None
        if record:
            self.placed += 1
            if self.metrics is not None:
                self.metrics.serving_fast_path_pods.inc()
        return name

    def warm(self, pi, snapshot, fwk) -> None:
        """Compile every serve-path program variant OFF the serve path:
        the plain solve and both fused refresh buckets (idempotent
        deltas — row 0 set to its current value). Called by the serving
        tier during its first batch dispatch so no measured lone-pod
        placement ever pays a jit. Deliberately compiles even when the
        warm pod itself has NO FIT (a failure-wave pod is a perfectly
        good shape donor) — bailing there once left the fused buckets
        cold, and their mid-serve compiles poisoned the tier's wall
        estimate."""
        backend = self.backend
        ct = backend._tensors(snapshot)
        if ct.n_real < 1 or ct.has_unknown_resource(pi.requests):
            return
        rows = self._base_rows(pi, snapshot, fwk, ct)
        if rows is None:
            return
        params = backend._fwk_params(fwk, ct)
        static = backend.ensure_static(ct)
        res = self.resident
        res.used_pack(ct, snapshot)  # ensure base + drain any pending
        tail = (self._base_mask(rows, ct), self._zero_score_row(ct),
                self._req_pack(pi, ct),
                params["fit_col_w"], params["bal_col_mask"],
                params["shape_u"], params["shape_s"],
                params["w_fit"], params["w_bal"], params["w_taint"],
                params["taint_filter_on"], params["strategy"])
        idx_d = solver.solve_one(
            static["alloc_q"], res._dev, static["alloc_pods"],
            static["taint_f"], static["taint_p"], *tail)
        np.asarray(idx_d)  # block: the compile finishes inside warmup
        for b in range(1, FUSE_MAX_ROWS + 1):
            idx_rows = np.zeros((b,), np.int32)
            vals = np.repeat(res._pack_np[:1], b, axis=0)
            _idx, pack = solver.solve_one_fresh(
                static["alloc_q"], res._dev, idx_rows, vals,
                static["alloc_pods"], static["taint_f"],
                static["taint_p"], *tail)
            res.adopt(pack)
        self.warmed = True
