"""Metrics: Prometheus-compatible counters/histograms with stability levels.

Parity target: staging/src/k8s.io/component-base/metrics (registry, stability
levels) + pkg/scheduler/metrics/metrics.go — the scheduler metric NAMES are a
contract for dashboard parity (SURVEY §5.5) and are preserved verbatim.

No prometheus_client dependency: a registry that renders the text exposition
format is ~100 lines and keeps the zero-install constraint.
"""

from __future__ import annotations

import bisect
import itertools
import math
from collections import defaultdict
from typing import Iterable, Mapping

from kubernetes_tpu.utils.locking import new_lock


def _esc_label(value) -> str:
    """Prometheus text-format label-value escaping (backslash, quote,
    newline) — exposition-format.md's only three escapes."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _esc_help(text: str) -> str:
    """HELP-line escaping: backslash and newline (quotes are legal there)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


class Counter:
    def __init__(self, name: str, help_: str = "", labels: Iterable[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(labels)
        self._values: dict[tuple, float] = defaultdict(float)
        self._lock = new_lock(f"metrics.{name}")

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] += amount

    def inc_key(self, key: tuple, amount: float = 1.0) -> None:
        """Hot-path increment with a caller-cached label tuple (skips
        per-call label-kwarg resolution; the policy engine incs once per
        expression per admitted request)."""
        with self._lock:
            self._values[key] += amount

    def value(self, **labels: str) -> float:
        key = tuple(labels.get(n, "") for n in self.label_names)
        return self._values.get(key, 0.0)

    def _render(self, type_: str) -> str:
        # The TYPE line is written explicitly per metric type: deriving it
        # by string replacement corrupted the HELP line whenever the help
        # text itself contained the word "counter".
        lines = [f"# HELP {self.name} {_esc_help(self.help)}",
                 f"# TYPE {self.name} {type_}"]
        # Snapshot under the lock: inc() runs in worker threads (the
        # backend's to_thread solve fetch observes metrics), and
        # iterating the live dict while one lands a NEW label key raises
        # "dictionary changed size during iteration" — the lock-hygiene
        # pass (LK205) caught this unlocked iteration.
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            lbl = ",".join(f'{n}="{_esc_label(val)}"'
                           for n, val in zip(self.label_names, key))
            lines.append(f"{self.name}{{{lbl}}} {v}" if lbl else f"{self.name} {v}")
        return "\n".join(lines)

    def render(self) -> str:
        return self._render("counter")


class Gauge(Counter):
    def set(self, value: float, **labels: str) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] = value

    def set_key(self, key: tuple, value: float) -> None:
        """Hot-path set with a caller-cached label tuple (the Counter
        inc_key idiom; the watch cache sets ring length per event)."""
        with self._lock:
            self._values[key] = value

    def render(self) -> str:
        return self._render("gauge")


_DEFAULT_BUCKETS = tuple(0.001 * (2 ** i) for i in range(16))  # 1ms .. ~32s


class Histogram:
    def __init__(self, name: str, help_: str = "", labels: Iterable[str] = (),
                 buckets: tuple[float, ...] = _DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.label_names = tuple(labels)
        self.buckets = buckets
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)
        self._totals: dict[tuple, int] = defaultdict(int)
        self._lock = new_lock(f"metrics.{name}")

    def observe(self, value: float, **labels: str) -> None:
        # Single-bucket increment (bisect); cumulative "le" semantics are
        # materialized at read time. The per-bucket loop here was measurable
        # at scheduler_perf scale (2-3 observes per pod x 16 buckets).
        key = tuple(labels.get(n, "") for n in self.label_names)
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * len(self.buckets)
            if i < len(counts):
                counts[i] += 1
            self._sums[key] += value
            self._totals[key] += 1

    def _cumulative(self, key: tuple) -> list[int]:
        counts = self._counts.get(key)
        if counts is None:
            return [0] * len(self.buckets)
        return list(itertools.accumulate(counts))

    def snapshot(self, **labels: str) -> tuple[list[int], int]:
        """(cumulative bucket counts, total) at this instant — pair with
        percentile_since for windowed percentiles (bench measured phase).
        Read under the lock: observe() runs in worker threads (the solve
        fetch), and a half-updated (counts, total) pair would misreport
        the window (the LK205 unlocked-read family)."""
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            return self._cumulative(key), self._totals.get(key, 0)

    def percentile(self, q: float, **labels: str) -> float:
        """Approximate percentile from bucket counts (for reports/bench)."""
        return self.percentile_since(
            q, ([0] * len(self.buckets), 0), **labels)

    def percentile_since(self, q: float, base: tuple[list[int], int],
                         **labels: str) -> float:
        """Percentile over observations made after `base = snapshot()`.

        Bucket counts are cumulative (observe() increments every bucket
        ≥ value), so the first bucket whose delta reaches the rank is the
        answer directly."""
        key = tuple(labels.get(n, "") for n in self.label_names)
        base_counts, base_total = base
        with self._lock:
            total = self._totals.get(key, 0) - base_total
            if key not in self._counts or total <= 0:
                return math.nan
            counts = self._cumulative(key)
        rank = q * total
        for i, (c, b) in enumerate(zip(counts, base_counts)):
            if c - b >= rank:
                return self.buckets[i]
        return self.buckets[-1]

    def count(self, **labels: str) -> int:
        key = tuple(labels.get(n, "") for n in self.label_names)
        return self._totals.get(key, 0)

    def sum(self, **labels: str) -> float:
        key = tuple(labels.get(n, "") for n in self.label_names)
        return self._sums.get(key, 0.0)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {_esc_help(self.help)}",
                 f"# TYPE {self.name} histogram"]
        # Consistent snapshot under the lock (see Counter._render): a
        # worker-thread observe() landing a new key mid-iteration raised,
        # and a sum/count torn across an observe misstates the series.
        with self._lock:
            series = [(key, self._cumulative(key), self._totals[key],
                       self._sums[key]) for key in sorted(self._totals)]
        for key, counts, total, sum_ in series:
            base = ",".join(f'{n}="{_esc_label(v)}"'
                            for n, v in zip(self.label_names, key))
            for b, c in zip(self.buckets, counts):
                sep = "," if base else ""
                lines.append(f'{self.name}_bucket{{{base}{sep}le="{b}"}} {c}')
            sep = "," if base else ""
            lines.append(f'{self.name}_bucket{{{base}{sep}le="+Inf"}} {total}')
            lines.append(f"{self.name}_sum{{{base}}} {sum_}")
            lines.append(f"{self.name}_count{{{base}}} {total}")
        return "\n".join(lines)


class WindowedLatencyRecorder:
    """Exact windowed percentiles from raw observations (ROADMAP #3's
    p999 prerequisite): a bounded ring of the last `capacity` values,
    read by (mark, percentiles_since) pairs the way the bench uses
    Histogram.snapshot/percentile_since — but returning TRUE order
    statistics instead of bucket edges, which a 16-bucket power-of-two
    histogram cannot resolve at p999.

    observe() is deliberately lock-free — one slot write + one integer
    increment, GIL-atomic in practice — so the recorder stays off the
    histogram lock's hot path; a racing observer can at worst overwrite
    one sample, never corrupt the ring. Windows larger than the capacity
    degrade to the newest `capacity` observations (the tail is what the
    high quantiles need)."""

    __slots__ = ("capacity", "_buf", "_n")

    def __init__(self, capacity: int = 1 << 17):
        self.capacity = capacity
        self._buf = [0.0] * capacity
        self._n = 0

    def observe(self, value: float) -> None:
        i = self._n
        self._buf[i % self.capacity] = value
        self._n = i + 1

    def mark(self) -> int:
        """Window-start marker; pass to percentiles_since."""
        return self._n

    def count_since(self, mark: int) -> int:
        return self._n - mark

    def percentiles_since(self, mark: int,
                          qs: Iterable[float]) -> dict[float, float]:
        """Exact percentiles over observations after `mark` (nearest-rank
        on the sorted window). NaN when the window is empty; windows
        beyond capacity use the newest `capacity` values."""
        n = self._n
        window = n - mark
        if window <= 0:
            return {q: math.nan for q in qs}
        take = min(window, self.capacity)
        cap = self.capacity
        if n <= cap:
            vals = self._buf[n - take:n]
        else:
            lo = (n - take) % cap
            hi = n % cap
            vals = self._buf[lo:] + self._buf[:hi] if lo >= hi \
                else self._buf[lo:hi]
        vals.sort()
        return {q: vals[min(max(math.ceil(q * take) - 1, 0), take - 1)]
                for q in qs}


class Registry:
    def __init__(self):
        self._metrics: dict[str, object] = {}

    def counter(self, name: str, help_: str = "", labels: Iterable[str] = ()) -> Counter:
        if name not in self._metrics:
            self._metrics[name] = Counter(name, help_, labels)
        return self._metrics[name]  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "", labels: Iterable[str] = ()) -> Gauge:
        if name not in self._metrics:
            self._metrics[name] = Gauge(name, help_, labels)
        return self._metrics[name]  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "", labels: Iterable[str] = (),
                  **kw) -> Histogram:
        if name not in self._metrics:
            self._metrics[name] = Histogram(name, help_, labels, **kw)
        return self._metrics[name]  # type: ignore[return-value]

    def render(self) -> str:
        return "\n".join(m.render() for m in self._metrics.values()) + "\n"


class WatchMetrics:
    """Watch-dispatch efficiency counters (the apiserver's
    `apiserver_watch_cache_*` family analog, SURVEY §3.3).

    The interned selector index (store/mvcc.py `_ResourceWatchers`) makes
    dispatch O(matching watchers); these counters are the evidence:
    `watch_predicate_checks_total` staying O(events) while watcher count
    grows is the regression guard, and dispatched/checks is the fan-out
    efficiency the bench detail JSON reports per run.
    """

    def __init__(self, registry: Registry | None = None):
        r = registry or Registry()
        self.registry = r
        self.events_dispatched = r.counter(
            "watch_events_dispatched_total",
            "Watch events delivered to watcher channels")
        self.predicate_checks = r.counter(
            "watch_predicate_checks_total",
            "Selector/field predicate evaluations during watch dispatch "
            "(one per interned selector group, one per index candidate)")
        self.index_hits = r.counter(
            "watch_index_hits_total",
            "Events routed through the tracked-field exact-value index")

    def register_into(self, registry: Registry) -> None:
        """Expose these counters through another registry's render: the
        store owns its WatchMetrics (private registry), the apiserver
        surfaces them at /metrics — same Counter objects, one source of
        truth."""
        for c in (self.events_dispatched, self.predicate_checks,
                  self.index_hits):
            registry._metrics.setdefault(c.name, c)


class WatchCacheMetrics:
    """Watch-cache serving-tier counters (the reference's
    `apiserver_watch_cache_*` / `apiserver_cache_list_*` families,
    SURVEY §L0): hits are LIST/watch-establishment requests answered
    from the RV-snapshotted cache, misses are requests the tier had to
    hand to the mvcc core (cold per-resource seed, backfill older than
    the ring), and `watch_cache_ring_len` is the per-resource replay
    ring depth — the "how much backfill can I serve" gauge. The bench
    detail JSON reports hit/miss deltas per measured phase; a relist
    storm that stays all-hits is the tier working."""

    def __init__(self, registry: Registry | None = None):
        r = registry or Registry()
        self.registry = r
        self.hits = r.counter(
            "watch_cache_hits_total",
            "LIST/watch requests served from the watch-cache tier "
            "without touching the mvcc core")
        self.misses = r.counter(
            "watch_cache_misses_total",
            "LIST/watch requests the watch-cache tier handed to the "
            "mvcc core (cold resource seed, pre-ring backfill)")
        self.ring_len = r.gauge(
            "watch_cache_ring_len",
            "Retained events in the per-resource watch-cache replay ring",
            labels=("resource",))

    def register_into(self, registry: Registry) -> None:
        """Surface these through a server registry's render (the
        WatchMetrics register_into pattern: same objects, one truth)."""
        for m in (self.hits, self.misses, self.ring_len):
            registry._metrics.setdefault(m.name, m)


class ChurnMetrics:
    """Churn-battery counters (perf/churn — ROADMAP #2's scenario
    battery): open-loop arrivals enqueued per model, fault-timeline
    events injected per kind, and summed time-to-recovery per kind.
    The injector/driver increment these; the bench detail JSON reports
    the per-phase deltas, and `register_into` surfaces them through a
    server registry's /metrics render (the WatchMetrics pattern: same
    objects, one truth)."""

    def __init__(self, registry: Registry | None = None):
        r = registry or Registry()
        self.registry = r
        self.arrivals = r.counter(
            "churn_arrivals_total",
            "Open-loop pod arrivals enqueued by the churn driver",
            labels=("model",))
        self.faults_injected = r.counter(
            "churn_faults_injected_total",
            "Fault-timeline events injected by the churn battery",
            labels=("kind",))
        self.recovery_seconds = r.counter(
            "churn_recovery_seconds_total",
            "Summed time-to-recovery of disruptive injected faults "
            "(displaced pods rescheduled, backlog under threshold)",
            labels=("kind",))
        self.backlog_peak = r.gauge(
            "churn_queue_backlog_peak",
            "Peak scheduler queue backlog observed during the latest "
            "open-loop churn phase")

    def register_into(self, registry: Registry) -> None:
        for m in (self.arrivals, self.faults_injected,
                  self.recovery_seconds, self.backlog_peak):
            registry._metrics.setdefault(m.name, m)


class DurabilityMetrics:
    """WAL + recovery counters (store/durable.py — SURVEY §5.4): events
    appended to the write-ahead log, fsync wall per group commit (the
    durability tax the fsync policy trades), and events replayed from
    WAL segments on recovery. The multi-process control plane fetches
    per-shard deltas over the wire's stats op; the bench detail JSON
    sums them per run."""

    def __init__(self, registry: Registry | None = None):
        r = registry or Registry()
        self.registry = r
        self.appends = r.counter(
            "wal_appends_total",
            "Committed events appended to the write-ahead log")
        self.fsync_seconds = r.histogram(
            "wal_fsync_seconds",
            "Wall time of each WAL fsync (per commit under "
            "fsync=always, per group-commit flush under fsync=batch)")
        self.replayed = r.counter(
            "wal_replay_entries_total",
            "WAL events replayed into a store during crash recovery")

    def register_into(self, registry: Registry) -> None:
        for m in (self.appends, self.fsync_seconds, self.replayed):
            registry._metrics.setdefault(m.name, m)


class HAMetrics:
    """Leader-election observability (client/leaderelection.py — SURVEY
    §5.3): elections won by this process and whether it currently holds
    the lease. The active/standby scheduler pair exposes these so a
    failover (standby's elections counter incrementing, the old
    leader's gauge dropping) is data, not log noise."""

    def __init__(self, registry: Registry | None = None):
        r = registry or Registry()
        self.registry = r
        self.elections = r.counter(
            "leader_elections_total",
            "Lease acquisitions won by this elector (first acquisition "
            "and every re-acquisition after losing the lease)")
        self.is_leader = r.gauge(
            "scheduler_is_leader",
            "1 while this scheduler process holds the leader lease, "
            "else 0")

    def register_into(self, registry: Registry) -> None:
        for m in (self.elections, self.is_leader):
            registry._metrics.setdefault(m.name, m)


class DeschedulerMetrics:
    """Rebalance-descheduler counters (controllers/descheduler.py):
    evict-and-replace consolidation moves actually issued. The
    disruption budget bounds the per-cycle delta; the ChurnDay
    rebalance family reports the phase total next to the
    fragmentation-over-time curve."""

    def __init__(self, registry: Registry | None = None):
        r = registry or Registry()
        self.registry = r
        self.evictions = r.counter(
            "descheduler_evictions_total",
            "Pods evicted (and re-created unbound) by the rebalance "
            "descheduler's consolidation moves")

    def register_into(self, registry: Registry) -> None:
        registry._metrics.setdefault(self.evictions.name, self.evictions)


#: verbs counted as mutating for apiserver_current_inflight_requests'
#: request_kind label (the reference's mutating/readOnly split).
_MUTATING_VERBS = frozenset(("create", "update", "patch", "delete"))


class APIServerMetrics:
    """The apiserver request metric families (SURVEY §5.5's dashboard
    contract): request latency by verb/resource/code and the in-flight
    gauge by request kind. Emitted from BOTH serving paths — the HTTP
    middleware chain and the KTPU wire's frame handler — into one shared
    instance, so /metrics shows the server's whole request load no matter
    which wire carried it. Long-running requests (watches) are excluded
    from both families: inflight like the reference, and duration
    because a watch's "latency" is its stream lifetime (and the two
    wires would otherwise report incompatible views of the same verb)."""

    def __init__(self, registry: Registry | None = None):
        r = registry or Registry()
        self.registry = r
        self.request_duration = r.histogram(
            "apiserver_request_duration_seconds",
            "Response latency distribution by verb, resource and "
            "HTTP-equivalent status code",
            labels=("verb", "resource", "code"))
        self.inflight = r.gauge(
            "apiserver_current_inflight_requests",
            "Currently executing (non-long-running) requests",
            labels=("request_kind",))

    def register_into(self, registry: Registry) -> None:
        for m in (self.request_duration, self.inflight):
            registry._metrics.setdefault(m.name, m)

    @staticmethod
    def _kind(verb: str) -> str:
        return "mutating" if verb in _MUTATING_VERBS else "readOnly"

    def observe(self, verb: str, resource: str, code: int,
                seconds: float) -> None:
        self.request_duration.observe(
            seconds, verb=verb, resource=resource, code=str(code))

    def inc_inflight(self, verb: str) -> None:
        self.inflight.inc(1, request_kind=self._kind(verb))

    def dec_inflight(self, verb: str) -> None:
        self.inflight.inc(-1, request_kind=self._kind(verb))


class SchedulerMetrics:
    """The scheduler's metric contract (pkg/scheduler/metrics/metrics.go)."""

    def __init__(self, registry: Registry | None = None):
        r = registry or Registry()
        self.registry = r
        self.schedule_attempts = r.counter(
            "scheduler_schedule_attempts_total",
            "Number of attempts to schedule pods, by result",
            labels=("result", "profile"))
        self.attempt_duration = r.histogram(
            "scheduler_scheduling_attempt_duration_seconds",
            "Scheduling attempt latency", labels=("result", "profile"))
        self.e2e_sli_duration = r.histogram(
            "scheduler_pod_scheduling_sli_duration_seconds",
            "E2E pod scheduling latency incl. queue time", labels=("attempts",))
        self.pending_pods = r.gauge(
            "scheduler_pending_pods", "Pending pods by queue",
            labels=("queue",))
        self.plugin_duration = r.histogram(
            "scheduler_plugin_execution_duration_seconds",
            "Per-plugin execution time",
            labels=("plugin", "extension_point"))
        self.extension_point_duration = r.histogram(
            "scheduler_framework_extension_point_duration_seconds",
            "Per-extension-point time", labels=("extension_point", "profile"))
        self.preemption_victims = r.histogram(
            "scheduler_preemption_victims", "Victims per preemption",
            buckets=(1, 2, 4, 8, 16, 32, 64))
        self.queue_incoming = r.counter(
            "scheduler_queue_incoming_pods_total",
            "Pods added to queues", labels=("event", "queue"))
        self.goroutines = r.gauge(
            "scheduler_goroutines", "Concurrent binding tasks", labels=("operation",))
        #: §5.5 explainability for the TPU backend's degraded modes, one
        #: increment per affected pod/gang: kind="spread_poisoned"
        #: (spread pod missed the union scan table — steady-state zero),
        #: kind="host_fallback" (pod took a per-pod host plugin row),
        #: kind="gang_overflow" (gangs beyond the solver's capacity
        #: degrade to Permit-barrier-only atomicity).
        self.backend_degradations = r.counter(
            "scheduler_tpu_backend_degradations_total",
            "TPU backend fallbacks to degraded modes", labels=("kind",))
        #: Solve-side observability (the r8 50k profile's blind spot: the
        #: device solve runs in XLA's compute threads, invisible to a
        #: main-thread sampler). Per-chunk wall of the fused solve as the
        #: consumer sees it, the width of the solver's per-step reduce
        #: (K + P when the shortlist prunes, N when it doesn't), and the
        #: shortlist's exactness-fallback accounting — hit rate is
        #: 1 - fallbacks/pods.
        self.solve_duration = r.histogram(
            "scheduler_tpu_solve_seconds",
            "Device-solve wall time per chunk (dispatch to fetched)")
        self.solver_scan_width = r.gauge(
            "scheduler_tpu_solver_scan_width",
            "Per-step candidate width of the latest chunk's solve")
        self.solver_shortlist_pods = r.counter(
            "scheduler_tpu_solver_shortlist_pods_total",
            "Pods solved through the shortlist-pruned scan")
        self.solver_shortlist_fallbacks = r.counter(
            "scheduler_tpu_solver_shortlist_fallbacks_total",
            "Pods whose shortlist bound check fell back to the full row")
        #: Block-sparse index observability: the two-pass prefilter's
        #: O(C·B) bound scan always walks every (class, block) pair —
        #: that is `scanned`; `pruned` counts the pairs whose columns
        #: the gather pass then NEVER touched because the block's score
        #: upper bound provably lost to the (K+1)-th shortlist value
        #: (prune rate = pruned/scanned; 0 on chunks where the
        #: exactness predicate forced the full-width prefilter). The
        #: refresh histogram is the serving tier's incremental
        #: per-block aggregate maintenance wall — O(changed blocks)
        #: per snapshot refresh, same dirty set as the resident planes.
        self.solver_blocks_scanned = r.counter(
            "scheduler_tpu_solver_blocks_scanned_total",
            "(class, block) pairs walked by the block-bound prefilter "
            "scan")
        self.solver_blocks_pruned = r.counter(
            "scheduler_tpu_solver_blocks_pruned_total",
            "(class, block) pairs the bound scan proved losers — their "
            "columns skipped the chunk-start score pass")
        self.solver_block_refresh = r.histogram(
            "scheduler_tpu_solver_block_refresh_seconds",
            "Wall time of one incremental block-aggregate refresh "
            "(dirty blocks only) on the resident planes")
        #: Wavefront-solve observability (r18): the wave width the latest
        #: chunk solved at (1 = serial scan — kill switch or narrowed
        #: policy), pods committed speculatively, and pods that fell
        #: into the exact serial replay. The replay fraction
        #: replays/(commits+replays) is the signal the AdaptiveTuner's
        #: width-narrowing rule keys on — recorded data, not a guess.
        self.solver_wave_width = r.gauge(
            "scheduler_tpu_solver_wave_width",
            "Pods evaluated per scan step by the latest chunk's solve")
        self.solver_wave_commits = r.counter(
            "scheduler_tpu_solver_wave_commits_total",
            "Pods committed speculatively by the wavefront solve")
        self.solver_wave_replays = r.counter(
            "scheduler_tpu_solver_wave_replays_total",
            "Pods placed through the wavefront solve's exact serial "
            "replay")
        #: Global-assignment observability (r20): chunks solved through
        #: the Sinkhorn transport plan + feasible rounding, chunks the
        #: tuner WANTED optimal but degraded to greedy (spread strategy
        #: or per-pod planes make the C x N plan ineligible), the
        #: iteration budget the latest optimal solve ran, and the
        #: cluster fragmentation the placement left behind — mean free
        #: fraction over OCCUPIED nodes, the quantity optimal mode
        #: packs down and the descheduler consolidates.
        self.solver_optimal_solves = r.counter(
            "solver_optimal_mode_solves_total",
            "Chunks solved through the Sinkhorn optimal-assignment mode")
        self.solver_optimal_fallbacks = r.counter(
            "solver_optimal_fallbacks_total",
            "Chunks routed to optimal mode that degraded to the greedy "
            "wavefront scan (ineligible planes or spread strategy)")
        self.solver_sinkhorn_iterations = r.gauge(
            "solver_sinkhorn_iterations",
            "Sinkhorn iteration budget of the latest optimal-mode solve")
        #: Pallas fused-kernel observability: chunks whose wavefront
        #: solve ran the fused kernel (interpret or compiled), and
        #: chunks where the router WANTED the kernel (KTPU_PALLAS
        #: resolved on) but fell back to the lax.scan reference — the
        #: reason label separates structural shapes the kernel does not
        #: fuse (spread/shortlist/optimal/wave_off/shape) from a
        #: backend without a pallas lowering (unavailable). The kill
        #: switch (KTPU_PALLAS=off) and the CPU auto default do NOT
        #: count: off-by-policy is not a fallback.
        self.solver_pallas_solves = r.counter(
            "solver_pallas_solves_total",
            "Chunks solved through the fused Pallas wavefront kernel")
        self.solver_pallas_fallbacks = r.counter(
            "solver_pallas_fallbacks_total",
            "Chunks routed to the Pallas kernel that fell back to the "
            "lax.scan reference", labels=("reason",))
        self.fragmentation_pct = r.gauge(
            "scheduler_fragmentation_pct",
            "Mean stranded-capacity fraction (pct) across occupied "
            "nodes after the latest measured run")
        #: Topology-slice observability (kubernetes_tpu/topology —
        #: ROADMAP #5's shaped-gang direction): gangs whose Permit
        #: contiguity check released a whole slice, the
        #: stranded-for-shape free capacity the latest slice plan saw
        #: (free cells NO feasible placement of the requested shape
        #: covers — the mesh analog of scheduler_fragmentation_pct),
        #: and coordinate-plane rebuilds (steady state: reuse, zero).
        self.slice_gangs_bound = r.counter(
            "scheduler_slice_gangs_bound_total",
            "Slice-shaped gangs released by Permit as one contiguous "
            "sub-mesh")
        self.slice_fragmentation_pct = r.gauge(
            "scheduler_slice_fragmentation_pct",
            "Free mesh cells covered by NO feasible placement of the "
            "most recently planned slice shape (pct)")
        self.topology_plane_rebuilds = r.counter(
            "topology_plane_rebuilds_total",
            "Rebuilds of the tensorized interconnect coordinate planes "
            "(mesh flags or node set moved; reuse does not count)")
        #: Sharded-control-plane observability (ROADMAP #5): per-shard
        #: host-prep rebuild counts (a shard increments only when its
        #: rows were actually rewritten — the incremental path's
        #: witness), the device-solve wall attributed to the sharded
        #: path (one fused program spans every shard on this hardware,
        #: so the label carries the shard COUNT the solve ran under,
        #: not a shard id), and the top-level cross-shard argmax
        #: reductions (one per pod step when S > 1).
        #: Class-dictionary device-plane observability (r14): host prep
        #: wall per chunk (the 200k bound the class planes attack — the
        #: prep-vs-solve split per family), real pod-equivalence classes
        #: behind the latest chunk's (C,N) planes (P on a per-pod
        #: fallback), bytes of plane payloads actually device_put
        #: (mask + score planes including cache fills, plus the per-chunk
        #: class index / exception / rep-row pack), and pods that rode a
        #: per-pod fallback because their chunk's distinct classes
        #: overflowed KTPU_CLASS_PAD (the kill switch does NOT count —
        #: only genuine class splits).
        self.prep_duration = r.histogram(
            "scheduler_tpu_prep_seconds",
            "Host-side chunk prep wall time (rows, classes, uploads)")
        self.plane_classes = r.gauge(
            "scheduler_tpu_plane_classes_per_chunk",
            "Pod equivalence classes behind the latest chunk's planes")
        self.plane_bytes = r.counter(
            "scheduler_tpu_plane_bytes_uploaded_total",
            "Bytes of mask/score plane payloads uploaded to the device")
        self.class_split_fallbacks = r.counter(
            "scheduler_tpu_class_split_fallbacks_total",
            "Pods solved through per-pod fallback planes after class "
            "overflow")
        self.shard_tensor_rebuilds = r.counter(
            "scheduler_tpu_shard_tensor_rebuilds_total",
            "Host-prep tensor rebuilds per control-plane shard",
            labels=("shard",))
        self.shard_solve_seconds = r.counter(
            "scheduler_tpu_shard_solve_seconds_total",
            "Device-solve wall under the sharded control plane",
            labels=("shards",))
        self.cross_shard_reductions = r.counter(
            "scheduler_tpu_cross_shard_reductions_total",
            "Top-level cross-shard argmax reductions (pod steps)")
        #: Serving-tier observability (kubernetes_tpu/serving, ROADMAP
        #: #3): the admission window's current coalesce hold (0 =
        #: dispatch-immediately), lone pods placed through the pinned
        #: C=1 fast path, dispatches whose window merged extra pods,
        #: and the resident device-plane refresh accounting (count +
        #: wall of the O(changed) delta requantize/scatter that
        #: replaces the per-assign full used-state upload).
        self.admission_window = r.gauge(
            "scheduler_admission_window_seconds",
            "Serving admission coalesce window applied to the latest "
            "dispatch (0 = immediate)")
        self.serving_fast_path_pods = r.counter(
            "serving_fast_path_pods_total",
            "Pods placed through the pinned single-pod fast path")
        self.serving_coalesced_batches = r.counter(
            "serving_coalesced_batches_total",
            "Dispatches whose admission window merged extra pods")
        self.resident_plane_refreshes = r.counter(
            "resident_plane_refreshes_total",
            "Refreshes of the device-resident used-state planes "
            "(incremental scatter or full rebuild)")
        self.resident_plane_refresh = r.histogram(
            "resident_plane_refresh_seconds",
            "Wall time of one resident-plane refresh (delta "
            "re-quantize + device scatter)")

        #: exact windowed percentile recorders riding attempt_duration's
        #: observe path, keyed by (result, profile) — the same population
        #: split as the histogram's labels, so the bench's exact
        #: percentiles replace the bucket-edge values one-for-one.
        #: Lock-free ring appends (see WindowedLatencyRecorder).
        self.attempt_windows: dict[
            tuple[str, str], WindowedLatencyRecorder] = {}

    def attempt_window(self, result: str = "scheduled",
                       profile: str = "default-scheduler") \
            -> WindowedLatencyRecorder:
        key = (result, profile)
        w = self.attempt_windows.get(key)
        if w is None:
            w = self.attempt_windows[key] = WindowedLatencyRecorder()
        return w

    def observe_plugin(self, plugin: str, point: str, seconds: float) -> None:
        self.plugin_duration.observe(seconds, plugin=plugin, extension_point=point)

    def observe_attempt(self, result: str, profile: str, seconds: float) -> None:
        self.schedule_attempts.inc(result=result, profile=profile)
        self.attempt_duration.observe(seconds, result=result, profile=profile)
        key = (result, profile)
        w = self.attempt_windows.get(key)
        if w is None:
            w = self.attempt_windows[key] = WindowedLatencyRecorder()
        w.observe(seconds)

    def set_pending(self, stats: Mapping[str, int]) -> None:
        for queue, n in stats.items():
            self.pending_pods.set(n, queue=queue)
