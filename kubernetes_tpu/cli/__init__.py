from kubernetes_tpu.cli.kubectl import main

__all__ = ["main"]
