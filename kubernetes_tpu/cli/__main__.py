from kubernetes_tpu.cli.kubectl import main

raise SystemExit(main())
