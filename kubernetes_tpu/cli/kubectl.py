"""ktpuctl: the kubectl-equivalent CLI (SURVEY §2.7).

Parity target: staging/src/k8s.io/kubectl `pkg/cmd/` — the operational
verbs an operator needs against the API server: get, describe, apply,
create, patch, diff, logs, delete, scale, cordon/uncordon, drain, top,
rollout.
Talks HTTP to an APIServer (`--server`), or to an in-process store when
a caller passes one (tests, embedded tools).

    python -m kubernetes_tpu.cli get pods -n default
    python -m kubernetes_tpu.cli apply -f manifest.yaml
    python -m kubernetes_tpu.cli create -f manifest.yaml
    python -m kubernetes_tpu.cli patch pods web -p '{"spec": {...}}'
    python -m kubernetes_tpu.cli diff -f manifest.yaml
    python -m kubernetes_tpu.cli logs web-1
    python -m kubernetes_tpu.cli drain node-3
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from typing import Any

from kubernetes_tpu.api.meta import (
    CLUSTER_SCOPED_RESOURCES,
    KIND_TO_RESOURCE,
    namespaced_name,
)
from kubernetes_tpu.store.mvcc import NotFound, StoreError

#: short names (kubectl's builtin aliases).
ALIASES = {
    "po": "pods", "no": "nodes", "ns": "namespaces",
    "deploy": "deployments", "rs": "replicasets", "sts": "statefulsets",
    "ds": "daemonsets", "pv": "persistentvolumes",
    "pvc": "persistentvolumeclaims", "sc": "storageclasses", "ev": "events",
}


def _resource(arg: str) -> str:
    return ALIASES.get(arg, arg)


def _cluster_scoped(store, resource: str) -> bool:
    # In-process stores know their own CRD-registered scopes; remote
    # clients fall back to the built-in set.
    f = getattr(store, "is_cluster_scoped", None)
    return f(resource) if f else resource in CLUSTER_SCOPED_RESOURCES


def _kind_map(store) -> dict:
    f = getattr(store, "kind_map", None)
    return f() if f else KIND_TO_RESOURCE


def _key(store, resource: str, name: str, namespace: str) -> str:
    if _cluster_scoped(store, resource):
        return name
    return f"{namespace}/{name}"


def _age(obj: dict) -> str:
    ts = obj.get("metadata", {}).get("creationTimestamp")
    if not ts:
        return "<none>"
    try:
        import datetime
        created = datetime.datetime.fromisoformat(ts.replace("Z", "+00:00"))
        secs = max(0, time.time() - created.timestamp())
    except ValueError:
        return "<invalid>"
    if secs < 120:
        return f"{int(secs)}s"
    if secs < 7200:
        return f"{int(secs // 60)}m"
    if secs < 172800:
        return f"{int(secs // 3600)}h"
    return f"{int(secs // 86400)}d"


def _pod_row(p: dict) -> list[str]:
    status = p.get("status", {}).get("phase", "Unknown")
    if p.get("metadata", {}).get("deletionTimestamp"):
        status = "Terminating"
    return [p["metadata"]["name"], status,
            p.get("spec", {}).get("nodeName") or "<none>", _age(p)]


def _node_row(n: dict) -> list[str]:
    ready = "Unknown"
    for c in n.get("status", {}).get("conditions") or []:
        if c.get("type") == "Ready":
            ready = "Ready" if c.get("status") == "True" else "NotReady"
    if n.get("spec", {}).get("unschedulable"):
        ready += ",SchedulingDisabled"
    return [n["metadata"]["name"], ready, _age(n)]


def _print_table(headers: list[str], rows: list[list[str]], out) -> None:
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    print("  ".join(h.ljust(w) for h, w in zip(headers, widths)), file=out)
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)), file=out)


def _dump(obj: Any, fmt: str, out) -> None:
    if fmt == "json":
        print(json.dumps(obj, indent=2), file=out)
    else:
        import yaml
        print(yaml.safe_dump(obj, sort_keys=False).rstrip(), file=out)


async def cmd_get(store, args, out) -> int:
    resource = _resource(args.resource)
    if args.name:
        try:
            obj = await store.get(resource,
                                  _key(store, resource, args.name, args.namespace))
        except NotFound as e:
            print(f"Error: {e}", file=sys.stderr)
            return 1
        if args.output in ("yaml", "json"):
            _dump(obj, args.output, out)
            return 0
        items = [obj]
    else:
        # Namespace filtering happens server-side (store.list supports
        # namespace=), not by transferring the whole cluster and sifting.
        ns = None if (_cluster_scoped(store, resource) or args.all_namespaces) \
            else args.namespace
        sel = None
        if args.selector:
            from kubernetes_tpu.api.labels import parse_selector
            sel = parse_selector(args.selector)
        lst = await store.list(resource, namespace=ns, selector=sel)
        items = lst.items
        if args.output in ("yaml", "json"):
            _dump({"kind": "List", "items": items}, args.output, out)
            return 0
    if resource == "pods":
        _print_table(["NAME", "STATUS", "NODE", "AGE"],
                     [_pod_row(o) for o in items], out)
    elif resource == "nodes":
        _print_table(["NAME", "STATUS", "AGE"],
                     [_node_row(o) for o in items], out)
    else:
        _print_table(["NAME", "AGE"],
                     [[o["metadata"]["name"], _age(o)] for o in items], out)
    return 0


async def cmd_describe(store, args, out) -> int:
    resource = _resource(args.resource)
    key = _key(store, resource, args.name, args.namespace)
    try:
        obj = await store.get(resource, key)
    except NotFound as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    _dump(obj, "yaml", out)
    # Trailing Events section (kubectl describe's most-used part).
    try:
        events = (await store.list("events")).items
    except StoreError:
        events = []
    want_kind = {k for k, r in _kind_map(store).items() if r == resource}
    related = []
    for e in events:
        inv = e.get("involvedObject") or {}
        if inv.get("name") != args.name:
            continue
        if inv.get("kind") and want_kind and inv["kind"] not in want_kind:
            continue
        if not _cluster_scoped(store, resource) and \
                inv.get("namespace", args.namespace) != args.namespace:
            continue
        related.append(e)
    if related:
        print("\nEvents:", file=out)
        for e in related[-10:]:
            print(f"  {e.get('type', '')}\t{e.get('reason', '')}\t"
                  f"{e.get('message', '')}", file=out)
    return 0


def _load_manifests(path: str) -> list[dict]:
    import yaml
    text = sys.stdin.read() if path == "-" else open(path).read()
    return [d for d in yaml.safe_load_all(text) if d]


async def cmd_apply(store, args, out) -> int:
    rc = 0
    for obj in _load_manifests(args.filename):
        resource = _kind_map(store).get(obj.get("kind", ""))
        if resource is None:
            print(f"Error: unknown kind {obj.get('kind')!r}", file=sys.stderr)
            rc = 1
            continue
        meta = obj.setdefault("metadata", {})
        if not _cluster_scoped(store, resource):
            meta.setdefault("namespace", args.namespace)
        if getattr(args, "server_side", False):
            # kubectl apply --server-side: field ownership + conflicts
            # live on the server (store/apply.py).
            from kubernetes_tpu.store.mvcc import Conflict
            try:
                await store.apply(
                    resource, obj,
                    field_manager=getattr(args, "field_manager", "kubectl"),
                    force=getattr(args, "force_conflicts", False))
                print(f"{resource}/{meta.get('name')} serverside-applied",
                      file=out)
            except Conflict as e:
                print(f"Error: {e}", file=sys.stderr)
                rc = 1
            continue
        key = _key(store, resource, meta.get("name", ""),
                   meta.get("namespace", args.namespace))
        try:
            current = await store.get(resource, key)
        except NotFound:
            await store.create(resource, obj)
            print(f"{resource}/{meta.get('name')} created", file=out)
            continue
        await store.update(resource, _apply_merge(current, obj))
        print(f"{resource}/{meta.get('name')} configured", file=out)
    return rc


def _apply_merge(current: dict, obj: dict) -> dict:
    """Client-side apply merge: replace spec-ish fields, keep
    server-owned metadata (shared by apply and diff)."""
    merged = dict(current)
    for k, v in obj.items():
        if k != "metadata":
            merged[k] = v
    merged["metadata"] = dict(current["metadata"])
    meta = obj.get("metadata") or {}
    for k in ("labels", "annotations"):
        if k in meta:
            merged["metadata"][k] = meta[k]
    return merged


async def cmd_diff(store, args, out) -> int:
    """kubectl diff (SURVEY §2.7): local manifests vs the server's live
    objects, with the desired state routed through the server's DRY-RUN
    admission chain (?dryRun=All) when the store supports it — the diff
    shows what admission mutation/defaulting would ACTUALLY persist,
    not the raw manifest. rc 0 = no differences, 1 = differences found,
    2 = error — e.g. admission REJECTED the desired state (kubectl's
    exit-code contract: >1 means the diff itself failed)."""
    import difflib

    import yaml
    differs = False
    errored = False
    for obj in _load_manifests(args.filename):
        resource = _kind_map(store).get(obj.get("kind", ""))
        if resource is None:
            print(f"Error: unknown kind {obj.get('kind')!r}",
                  file=sys.stderr)
            errored = True
            continue
        meta = obj.setdefault("metadata", {})
        if not _cluster_scoped(store, resource):
            meta.setdefault("namespace", args.namespace)
        name = meta.get("name", "")
        key = _key(store, resource, name, meta.get("namespace",
                                                   args.namespace))
        try:
            live = await store.get(resource, key)
        except NotFound:
            live = None
        desired = obj if live is None else _apply_merge(live, obj)
        dry = getattr(store, "dry_run", None)
        if dry is not None:
            try:
                desired = await dry(
                    resource, desired,
                    "create" if live is None else "update")
            except StoreError as e:
                print(f"Error: {resource}/{name} rejected by the "
                      f"dry-run admission chain: {e}", file=sys.stderr)
                errored = True
                continue
        a = yaml.safe_dump(live, sort_keys=True).splitlines() if live \
            else []
        b = yaml.safe_dump(desired, sort_keys=True).splitlines()
        diff = list(difflib.unified_diff(
            a, b, fromfile=f"LIVE/{resource}/{name}",
            tofile=f"MERGED/{resource}/{name}", lineterm=""))
        if diff:
            differs = True
            for line in diff:
                print(line, file=out)
    if errored:
        return 2
    return 1 if differs else 0


async def cmd_logs(store, args, out) -> int:
    """kubectl logs, minimal read path: there is no container runtime,
    so the "log" is reconstructed from the agent-recorded status — the
    hollow kubelet's phase/podIP/condition writes (agent/agent.py
    _mark_running) — followed by the pod's recorded events."""
    key = _key(store, "pods", args.name, args.namespace)
    try:
        pod = await store.get("pods", key)
    except NotFound as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    meta = pod.get("metadata") or {}
    spec = pod.get("spec") or {}
    status = pod.get("status") or {}
    if meta.get("creationTimestamp"):
        print(f"created {meta['creationTimestamp']}", file=out)
    if spec.get("nodeName"):
        print(f"scheduled to node {spec['nodeName']}", file=out)
    for c in status.get("conditions") or []:
        print(f"condition {c.get('type')}={c.get('status')}", file=out)
    if status.get("podIP"):
        print(f"podIP {status['podIP']}", file=out)
    print(f"phase {status.get('phase', 'Unknown')}", file=out)
    try:
        events = (await store.list("events")).items
    except StoreError:
        events = []
    for e in events:
        inv = e.get("involvedObject") or {}
        if inv.get("kind") not in (None, "Pod") or \
                inv.get("name") != args.name:
            continue
        if inv.get("namespace", args.namespace) != args.namespace:
            continue
        print(f"event {e.get('type', '')} {e.get('reason', '')}: "
              f"{e.get('message', '')}", file=out)
    return 0


async def cmd_create(store, args, out) -> int:
    """kubectl create -f: create-only (unlike apply, an existing object
    is an error — pkg/cmd/create semantics)."""
    rc = 0
    for obj in _load_manifests(args.filename):
        resource = _kind_map(store).get(obj.get("kind", ""))
        if resource is None:
            print(f"Error: unknown kind {obj.get('kind')!r}",
                  file=sys.stderr)
            rc = 1
            continue
        meta = obj.setdefault("metadata", {})
        if not _cluster_scoped(store, resource):
            meta.setdefault("namespace", args.namespace)
        try:
            await store.create(resource, obj)
            print(f"{resource}/{meta.get('name')} created", file=out)
        except StoreError as e:
            print(f"Error: {e}", file=sys.stderr)
            rc = 1
    return rc


async def cmd_patch(store, args, out) -> int:
    """kubectl patch: strategic-merge (default) / merge / json patch.
    Against a RemoteStore the server merges and the result runs the
    FULL admission chain (webhooks + expression policies); in-process
    stores fall back to a local merge + guaranteed_update."""
    resource = _resource(args.resource)
    key = _key(store, resource, args.name, args.namespace)
    try:
        patch = json.loads(args.patch)
    except json.JSONDecodeError as e:
        print(f"Error: bad patch JSON: {e}", file=sys.stderr)
        return 1
    try:
        remote_patch = getattr(store, "patch", None)
        if remote_patch is not None:
            await remote_patch(resource, key, patch,
                               patch_type=args.type)
        elif args.type == "json":
            from kubernetes_tpu.apiserver.admission import (
                apply_json_patch,
            )
            await store.guaranteed_update(
                resource, key,
                lambda cur: apply_json_patch(cur, patch),
                return_copy=False)
        else:
            from kubernetes_tpu.store.apply import strategic_merge_patch
            await store.guaranteed_update(
                resource, key,
                lambda cur: strategic_merge_patch(
                    cur, patch, strategic=args.type == "strategic"),
                return_copy=False)
    except StoreError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"{resource}/{args.name} patched", file=out)
    return 0


async def cmd_delete(store, args, out) -> int:
    if args.filename:
        rc = 0
        for obj in _load_manifests(args.filename):
            resource = _kind_map(store).get(obj.get("kind", ""))
            if resource is None:
                print(f"Error: unknown kind {obj.get('kind')!r}",
                      file=sys.stderr)
                rc = 1
                continue
            meta = obj.get("metadata", {})
            key = _key(store, resource, meta.get("name", ""),
                       meta.get("namespace", args.namespace))
            try:
                await store.delete(resource, key)
                print(f"{resource}/{meta.get('name')} deleted", file=out)
            except StoreError as e:
                print(f"Error: {e}", file=sys.stderr)
                rc = 1
        return rc
    resource = _resource(args.resource)
    try:
        await store.delete(resource,
                           _key(store, resource, args.name, args.namespace))
    except StoreError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"{resource}/{args.name} deleted", file=out)
    return 0


async def cmd_scale(store, args, out) -> int:
    resource = _resource(args.resource)
    key = _key(store, resource, args.name, args.namespace)

    def mutate(obj):
        if resource == "jobs":
            obj.setdefault("spec", {})["parallelism"] = args.replicas
        else:
            obj.setdefault("spec", {})["replicas"] = args.replicas
        return obj
    try:
        await store.guaranteed_update(resource, key, mutate,
                                     return_copy=False)
    except StoreError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"{resource}/{args.name} scaled to {args.replicas}", file=out)
    return 0


async def _set_unschedulable(store, node: str, value: bool) -> None:
    def mutate(obj):
        if value:
            obj.setdefault("spec", {})["unschedulable"] = True
        else:
            obj.get("spec", {}).pop("unschedulable", None)
        return obj
    await store.guaranteed_update("nodes", node, mutate, return_copy=False)


async def cmd_cordon(store, args, out) -> int:
    await _set_unschedulable(store, args.node, True)
    print(f"node/{args.node} cordoned", file=out)
    return 0


async def cmd_uncordon(store, args, out) -> int:
    await _set_unschedulable(store, args.node, False)
    print(f"node/{args.node} uncordoned", file=out)
    return 0


async def cmd_drain(store, args, out) -> int:
    """cordon + evict: delete the node's pods except DaemonSet-owned
    (kubectl drain --ignore-daemonsets semantics)."""
    await _set_unschedulable(store, args.node, True)
    pods = (await store.list("pods")).items
    failed = 0
    for p in pods:
        if p.get("spec", {}).get("nodeName") != args.node:
            continue
        refs = p.get("metadata", {}).get("ownerReferences") or []
        if any(r.get("kind") == "DaemonSet" for r in refs):
            continue
        try:
            # Eviction API first (honors PodDisruptionBudgets); plain
            # delete when the subresource isn't installed.
            try:
                await store.subresource(
                    "pods", namespaced_name(p), "eviction", {})
            except NotFound as e:
                if "not registered" not in str(e):
                    raise
                await store.delete("pods", namespaced_name(p))
            print(f"pod/{p['metadata']['name']} evicted", file=out)
        except StoreError as e:
            failed += 1
            print(f"Error evicting {p['metadata']['name']}: {e}",
                  file=sys.stderr)
    if failed:
        print(f"Error: {failed} pod(s) could not be evicted from "
              f"{args.node}", file=sys.stderr)
        return 1
    print(f"node/{args.node} drained", file=out)
    return 0


async def cmd_top(store, args, out) -> int:
    """top nodes|pods: requested/allocatable (the scheduler's view —
    there is no metrics-server; requests are the capacity signal here)."""
    from kubernetes_tpu.api.resource import format_quantity, parse_quantity
    from kubernetes_tpu.api.types import pod_is_terminal, pod_requests
    if args.what == "pods":
        rows = []
        for p in (await store.list(
                "pods", namespace=args.namespace)).items:
            if pod_is_terminal(p):
                continue
            reqs = pod_requests(p)
            rows.append([
                p["metadata"]["name"],
                format_quantity(reqs.get("cpu", 0)),
                format_quantity(reqs.get("memory", 0)),
                p.get("spec", {}).get("nodeName", "<none>"),
            ])
        _print_table(["NAME", "CPU(req)", "MEM(req)", "NODE"], rows, out)
        return 0
    nodes = (await store.list("nodes")).items
    pods = (await store.list("pods")).items
    used: dict[str, dict[str, int]] = {}
    for p in pods:
        node = p.get("spec", {}).get("nodeName")
        if not node or pod_is_terminal(p):
            continue  # Succeeded/Failed pods hold no capacity
        agg = used.setdefault(node, {})
        for r, v in pod_requests(p).items():
            agg[r] = agg.get(r, 0) + v
    rows = []
    for n in nodes:
        name = n["metadata"]["name"]
        alloc = n.get("status", {}).get("allocatable") or {}
        cpu_a = parse_quantity(alloc.get("cpu", 0))
        mem_a = parse_quantity(alloc.get("memory", 0))
        cpu_u = used.get(name, {}).get("cpu", 0)
        mem_u = used.get(name, {}).get("memory", 0)
        rows.append([
            name,
            f"{format_quantity(cpu_u)}/{format_quantity(cpu_a)}",
            f"{100 * cpu_u // cpu_a if cpu_a else 0}%",
            f"{format_quantity(mem_u)}/{format_quantity(mem_a)}",
            f"{100 * mem_u // mem_a if mem_a else 0}%",
        ])
    _print_table(["NAME", "CPU(req/alloc)", "CPU%",
                  "MEM(req/alloc)", "MEM%"], rows, out)
    return 0



async def cmd_rollout(store, args, out) -> int:
    """rollout status|restart|history for deployments (kubectl rollout).

    status: observedGeneration + updated/ready vs desired (the reference
    rollout_status.go readiness math); restart: stamps
    kubectl.kubernetes.io/restartedAt into the pod template, which hashes
    to a new revision and rolls every pod (kubectl rollout restart).
    """
    from kubernetes_tpu.api.meta import now_iso
    if args.resource not in ("deployment", "deployments"):
        print("Error: rollout supports deployments", file=sys.stderr)
        return 1
    key = _key(store, "deployments", args.name, args.namespace)
    try:
        dep = await store.get("deployments", key)
    except NotFound:
        print(f"Error: deployment {args.name!r} not found", file=sys.stderr)
        return 1
    if args.action == "status":
        spec = dep.get("spec") or {}
        status = dep.get("status") or {}
        desired = int(spec.get("replicas", 1))
        updated = int(status.get("updatedReplicas", 0))
        ready = int(status.get("readyReplicas", 0))
        gen_ok = int(status.get("observedGeneration", 0)) >= \
            int(dep["metadata"].get("generation", 0) or 0)
        if gen_ok and updated == desired and ready == desired:
            print(f'deployment "{args.name}" successfully rolled out',
                  file=out)
            return 0
        print(f"Waiting for deployment {args.name!r} rollout to finish: "
              f"{updated} out of {desired} new replicas have been "
              f"updated, {ready} ready...", file=out)
        return 3  # kubectl's non-zero while in progress (watch loop)
    if args.action == "restart":
        stamp = now_iso()

        def bump(obj):
            tmpl = obj.setdefault("spec", {}).setdefault("template", {})
            md = tmpl.setdefault("metadata", {})
            md.setdefault("annotations", {})[
                "kubectl.kubernetes.io/restartedAt"] = stamp
            return obj
        await store.guaranteed_update("deployments", key, bump,
                                      return_copy=False)
        print(f"deployment.apps/{args.name} restarted", file=out)
        return 0
    if args.action == "history":
        rss = (await store.list("replicasets",
                                namespace=args.namespace)).items
        rows = []
        for rs in rss:
            for ref in rs["metadata"].get("ownerReferences") or []:
                if ref.get("kind") == "Deployment" and \
                        ref.get("name") == args.name:
                    rows.append([
                        rs["metadata"].get("annotations", {}).get(
                            "deployment.kubernetes.io/revision", "?"),
                        rs["metadata"]["name"],
                        str(rs.get("spec", {}).get("replicas", 0)),
                    ])
        rows.sort(key=lambda r: int(r[0]) if r[0].isdigit() else 1 << 30)
        _print_table(["REVISION", "REPLICASET", "REPLICAS"], rows, out)
        return 0
    print(f"Error: unknown rollout action {args.action!r}",
          file=sys.stderr)
    return 1


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="ktpuctl", description=__doc__)
    ap.add_argument("--server", "-s", default="http://127.0.0.1:8080",
                    help="API server URL")
    ap.add_argument("--token", default=None, help="bearer token")
    ap.add_argument("--namespace", "-n", default="default")
    sub = ap.add_subparsers(dest="command", required=True)

    g = sub.add_parser("get")
    g.add_argument("resource")
    g.add_argument("name", nargs="?")
    g.add_argument("-o", "--output", choices=["table", "yaml", "json"],
                   default="table")
    g.add_argument("-l", "--selector", default=None)
    g.add_argument("-A", "--all-namespaces", action="store_true")
    g.set_defaults(fn=cmd_get)

    d = sub.add_parser("describe")
    d.add_argument("resource")
    d.add_argument("name")
    d.set_defaults(fn=cmd_describe)

    a = sub.add_parser("apply")
    a.add_argument("-f", "--filename", required=True)
    a.add_argument("--server-side", action="store_true",
                   help="server-side apply: declarative field ownership "
                        "with managedFields + conflict detection")
    a.add_argument("--field-manager", default="kubectl",
                   help="field owner name for --server-side")
    a.add_argument("--force-conflicts", action="store_true",
                   help="take ownership of conflicting fields")
    a.set_defaults(fn=cmd_apply)

    cr = sub.add_parser("create")
    cr.add_argument("-f", "--filename", required=True)
    cr.set_defaults(fn=cmd_create)

    df = sub.add_parser("diff")
    df.add_argument("-f", "--filename", required=True)
    df.set_defaults(fn=cmd_diff)

    lg = sub.add_parser("logs")
    lg.add_argument("name")
    lg.set_defaults(fn=cmd_logs)

    pa = sub.add_parser("patch")
    pa.add_argument("resource")
    pa.add_argument("name")
    pa.add_argument("-p", "--patch", required=True,
                    help="patch document (JSON)")
    pa.add_argument("--type", choices=["strategic", "merge", "json"],
                    default="strategic")
    pa.set_defaults(fn=cmd_patch)

    rm = sub.add_parser("delete")
    rm.add_argument("resource", nargs="?")
    rm.add_argument("name", nargs="?")
    rm.add_argument("-f", "--filename", default=None)
    rm.set_defaults(fn=cmd_delete)

    sc = sub.add_parser("scale")
    sc.add_argument("resource")
    sc.add_argument("name")
    sc.add_argument("--replicas", type=int, required=True)
    sc.set_defaults(fn=cmd_scale)

    for verb, fn in (("cordon", cmd_cordon), ("uncordon", cmd_uncordon),
                     ("drain", cmd_drain)):
        c = sub.add_parser(verb)
        c.add_argument("node")
        c.set_defaults(fn=fn)

    t = sub.add_parser("top")
    t.add_argument("what", choices=["nodes", "pods"])
    t.set_defaults(fn=cmd_top)

    ro = sub.add_parser("rollout")
    ro.add_argument("action", choices=["status", "restart", "history"])
    ro.add_argument("resource")
    ro.add_argument("name")
    ro.set_defaults(fn=cmd_rollout)
    return ap


async def run_command(store, args, out=None) -> int:
    """Entry for tests/embedding: run one parsed command against any
    MVCCStore-shaped object (RemoteStore or in-process)."""
    return await args.fn(store, args, out or sys.stdout)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    async def body() -> int:
        from kubernetes_tpu.apiserver.client import RemoteStore
        rs = RemoteStore(args.server, token=args.token)
        try:
            try:
                # Learn CRD kinds/scopes from server discovery (RESTMapper
                # pattern); a failed fetch just leaves the built-ins.
                await rs.refresh_discovery()
            except Exception:
                pass
            return await run_command(rs, args)
        finally:
            await rs.close()

    try:
        return asyncio.run(body())
    except StoreError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except OSError as e:  # file not found, connection refused, ...
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except ValueError as e:  # bad selector / quantity / YAML scalar
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except Exception as e:  # aiohttp client errors etc. — one line, rc 1
        print(f"Error: {type(e).__name__}: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
