"""Shard apiserver process: one mvcc store + WAL behind a wire socket.

`shard_main` is the spawn-child entrypoint `MultiProcessControlPlane`
launches once per shard: it owns ONE unsharded `MVCCStore` (its own
r12 watch-cache tier and event ring), allocates RVs from the shared
cross-process counter (multiproc/rv.py), journals every commit to a
per-shard write-ahead log under `<data_dir>/shard-<i>/`, and serves
the KTPU wire on a unix socket. The parent's `ProcessShardedStore`
(multiproc/client.py) routes to these sockets with the same hash
table the in-process facade uses.

The child never imports jax (the store/apiserver layers are jax-free
by construction — the import-graph lint pins that), so a shard
process boots in interpreter-start time, not jit-compile time.

Restart-after-crash: the parent respawns with the same socket path,
data dir, and shared counter; `recover_store` rebuilds from the
newest snapshot + WAL tail, and the monotonic counter setter
guarantees replay never regresses RVs other shards handed out.
"""

from __future__ import annotations

import asyncio
import os
import signal


def shard_main(index: int, socket_path: str, rv_counter,
               data_dir: str | None, env: dict) -> None:
    """Process target (must stay a module-level function: spawn pickles
    it by qualified name). Blocks until SIGTERM/SIGINT."""
    os.environ.update(env)
    asyncio.run(_serve(index, socket_path, rv_counter, data_dir))


async def _serve(index: int, socket_path: str, rv_counter,
                 data_dir: str | None) -> None:
    from kubernetes_tpu.apiserver.wire import WireServer
    from kubernetes_tpu.metrics.registry import DurabilityMetrics
    from kubernetes_tpu.store import install_core_validation
    from kubernetes_tpu.store.durable import DurabilityManager, recover_store
    from kubernetes_tpu.store.mvcc import MVCCStore, binding_subresource

    metrics = DurabilityMetrics()
    durability = None
    if data_dir:
        shard_dir = os.path.join(data_dir, f"shard-{index}")
        os.makedirs(shard_dir, exist_ok=True)
        store = recover_store(shard_dir, rv_source=rv_counter,
                              metrics=metrics)
        durability = DurabilityManager(store, shard_dir, metrics=metrics)
    else:
        store = MVCCStore(rv_source=rv_counter)
        store.register_subresource("pods", "binding", binding_subresource)
    install_core_validation(store)

    # A crashed predecessor (SIGKILL) leaves its socket file behind;
    # binding over it needs the unlink first.
    try:
        os.unlink(socket_path)
    except OSError:
        pass

    server = WireServer(store, host=f"unix:{socket_path}")

    def _stats() -> dict:
        return {
            "shard": index,
            "rv": store.resource_version,
            "objects": sum(len(t) for t in store._tables.values()),
            "walAppends": int(metrics.appends.value()),
            "walReplayed": int(metrics.replayed.value()),
            "walFsyncs": int(metrics.fsync_seconds.count()),
            "walFsyncSeconds": round(metrics.fsync_seconds.sum(), 6),
        }

    server.stats_fn = _stats
    await server.start()
    if durability is not None:
        durability.start()

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()

    # Graceful drain: final snapshot so the next boot replays nothing.
    if durability is not None:
        await durability.stop(final_snapshot=True)
    await server.stop()
    store.stop()
