"""Scheduler process: leader-elected active/standby pair.

`sched_main` is the spawn-child entrypoint for one scheduler replica.
Each replica builds a `ProcessShardedStore` over the shard sockets
and blocks in `LeaderElector.run` on the `ktpu-scheduler` Lease
(client/leaderelection.py — lease CAS, KTPU_LEASE_DURATION clock).
Only the LEADER constructs the Scheduler, rebuilds its assume-cache
from fresh informer LISTs (the reference's behavior: scheduler cache
state is never replicated, it is REBUILT on failover), and schedules;
the standby holds no informers and costs nothing until the lease
frees.

Measurement rides the store, not a side channel: the parent writes a
marker ConfigMap (`kube-system/ktpu-measure`, `{id, op}`) and the
leader's status loop answers on `kube-system/ktpu-sched-status` with
the acked marker id, its scheduled count, and — after an `end`
marker — exact attempt percentiles over the marked window (the r11
WindowedLatencyRecorder, same recorder the in-process harness
reads). After a failover the new leader marks from ITS window start,
so percentiles cover the post-failover tail — honest, and visible in
the detail JSON via `leader_elections_total` > 1.

The replica imports jax only when the parent requests a device
backend — a host-path scheduler pair boots in interpreter time.
"""

from __future__ import annotations

import asyncio
import os
import signal

MARKER_KEY = "kube-system/ktpu-measure"
STATUS_KEY = "kube-system/ktpu-sched-status"
STATUS_PERIOD_S = 0.1


def sched_main(identity: str, targets: list, env: dict,
               backend_spec: dict | None = None, batch_size: int = 1,
               scheduler_kwargs: dict | None = None) -> None:
    """Process target (module-level for spawn pickling). Blocks until
    SIGTERM/SIGINT; the active replica additionally dies with the
    whole process on kill_leader() — that is the point."""
    os.environ.update(env)
    asyncio.run(_replica(identity, list(targets), backend_spec,
                         batch_size, dict(scheduler_kwargs or {})))


async def _replica(identity: str, targets: list,
                   backend_spec: dict | None, batch_size: int,
                   scheduler_kwargs: dict) -> None:
    from kubernetes_tpu.client.leaderelection import LeaderElector
    from kubernetes_tpu.multiproc.client import ProcessShardedStore

    store = ProcessShardedStore(targets)
    backend = None
    if backend_spec and backend_spec.get("kind") == "tpu":
        from kubernetes_tpu.ops import TPUBackend
        backend = TPUBackend(max_batch=backend_spec.get("chunk"))

    elector = LeaderElector(store, "ktpu-scheduler", identity)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)

    async def lead() -> None:
        await _lead(store, identity, backend, batch_size,
                    scheduler_kwargs, elector)

    run_task = asyncio.ensure_future(elector.run(lead))
    stop_task = asyncio.ensure_future(stop.wait())
    await asyncio.wait({run_task, stop_task},
                       return_when=asyncio.FIRST_COMPLETED)
    run_task.cancel()
    await asyncio.gather(run_task, return_exceptions=True)
    stop_task.cancel()
    await store.close()


async def _lead(store, identity: str, backend, batch_size: int,
                scheduler_kwargs: dict, elector) -> None:
    """The leader payload: assume-cache rebuild (fresh informers), the
    scheduling loop, and the status/marker responder."""
    from kubernetes_tpu.client.informer import InformerFactory
    from kubernetes_tpu.metrics.registry import SchedulerMetrics
    from kubernetes_tpu.scheduler.scheduler import Scheduler

    metrics = SchedulerMetrics()
    metrics.registry._metrics.setdefault(
        "leader_elections_total", elector.metrics.elections)
    metrics.registry._metrics.setdefault(
        "scheduler_is_leader", elector.metrics.is_leader)
    sched = Scheduler(store, seed=42, backend=backend, metrics=metrics,
                      **scheduler_kwargs)
    factory = InformerFactory(store)
    await sched.setup_informers(factory)
    factory.start()
    # The stretch presets put ~1M objects behind this sync: LIST +
    # decode over the wire is minutes, not seconds, on a narrow box. A
    # tight timeout here turns "slow sync" into a leader crash-loop
    # (payload dies -> lease expires -> standby dies the same way), so
    # the deadline only guards against a truly wedged apiserver.
    await factory.wait_for_sync(timeout=900.0)
    status = asyncio.ensure_future(
        _status_loop(store, identity, metrics, elector))
    try:
        await sched.run(batch_size=batch_size)
    finally:
        status.cancel()
        await asyncio.gather(status, return_exceptions=True)
        await sched.stop()
        factory.stop()


async def _status_loop(store, identity: str, metrics, elector) -> None:
    """Answer measure markers and publish leader status via ConfigMaps.
    Store writes ride the meta shard like any client's — no side
    channel to keep alive across failover."""
    from kubernetes_tpu.api.meta import new_object
    from kubernetes_tpu.store.mvcc import NotFound, StoreError

    win = metrics.attempt_window()
    mark: int | None = None
    acked = ""
    pcts: dict | None = None
    while True:
        try:
            try:
                marker = (await store.get(
                    "configmaps", MARKER_KEY)).get("data") or {}
            except NotFound:
                marker = {}
            mid = str(marker.get("id", ""))
            if mid and mid != acked:
                if marker.get("op") == "begin":
                    mark = win.mark()
                    pcts = None
                elif mark is not None:
                    pcts = win.percentiles_since(
                        mark, (0.50, 0.90, 0.99, 0.999))
                acked = mid
            data = {
                "identity": identity,
                "ackId": acked,
                "isLeader": "1" if elector.is_leader else "0",
                "elections": str(int(elector.metrics.elections.value())),
                "scheduledTotal": str(int(metrics.schedule_attempts.value(
                    result="scheduled", profile="default-scheduler"))),
            }
            if pcts is not None:
                for q, label in ((0.50, "p50"), (0.90, "p90"),
                                 (0.99, "p99"), (0.999, "p999")):
                    data[label] = repr(pcts[q])

            def put(obj):
                obj["data"] = data
                return obj

            try:
                await store.guaranteed_update("configmaps", STATUS_KEY, put)
            except NotFound:
                cm = new_object("ConfigMap", "ktpu-sched-status",
                                "kube-system")
                cm["data"] = data
                await store.create("configmaps", cm)
        except asyncio.CancelledError:
            raise
        except StoreError:
            pass  # transient (shard restarting): retry next tick
        await asyncio.sleep(STATUS_PERIOD_S)
