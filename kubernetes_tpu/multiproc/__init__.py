"""Multi-process control plane (ISSUE r22 tentpole).

The in-process sharded store (store/sharded.py) divides the O(table)
costs by S but every shard still shares one interpreter, one GIL, and
one event loop. This package moves each shard into its own OS
process behind the existing KTPU wire, the scheduler into a
leader-elected active/standby pair, and the shared RVCounter into
shared memory:

- rv.py            — `SharedRVCounter`: atomic int64 in shared memory,
                     monotonic setter (recovery can't regress RVs).
- shardproc.py     — shard apiserver child: mvcc store + r12 cacher +
                     per-shard WAL + wire socket.
- schedproc.py     — scheduler replica child: Lease-elected leader
                     rebuilds its assume-cache from informers.
- client.py        — `ProcessShardedStore`: the MVCCStore-shaped
                     facade routing over the shard sockets.
- controlplane.py  — `MultiProcessControlPlane`: spawn/kill/restart
                     supervisor + the measure-marker protocol.

Activation: bench.py `--processes N` / KTPU_PROCESSES. `1` is the
kill switch — the in-process tree is built exactly as before (no
facade, no children), so degradation is structural.
"""

from kubernetes_tpu.multiproc.client import ProcessShardedStore
from kubernetes_tpu.multiproc.controlplane import (
    MeasureProtocol,
    MultiProcessControlPlane,
)
from kubernetes_tpu.multiproc.rv import SharedRVCounter

__all__ = [
    "MeasureProtocol",
    "MultiProcessControlPlane",
    "ProcessShardedStore",
    "SharedRVCounter",
]
