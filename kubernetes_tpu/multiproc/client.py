"""Cross-process sharded store facade.

`ProcessShardedStore` is the multi-process twin of
store/sharded.py's `ShardedNodeStore`: the same MVCCStore-shaped
surface and the same routing table (node-keyed resources hash to
shard `crc32(name) % S`, everything else lives on the meta shard),
but each shard is a `WireStore` client to a separate apiserver
PROCESS (multiproc/shardproc.py) instead of an in-process MVCCStore.
Informers, the scheduler, controllers, and the bench harness consume
it unchanged — `ShardedInformer` sees the same
`control_topology()` / `list(shard=)` / `watch(shard=)` seams.

One contract is deliberately weaker than the in-process facade's:
a merged LIST here fans out over real sockets, so the per-shard
snapshots are NOT taken in one event-loop tick. Each shard's page is
individually consistent and the merged RV is the max across shards —
a watcher resuming from it can never miss an event (every shard's
snapshot is at-or-before that RV), but the merged page is not a
single global point-in-time cut. The in-process facade keeps the
bit-identical-to-single-store guarantee (its differential test is
unchanged); the cross-process differential (tests/test_multiproc.py)
asserts equality against a quiesced store, where the distinction
vanishes.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Callable, Mapping

from kubernetes_tpu.api.labels import Selector
from kubernetes_tpu.metrics.registry import WatchMetrics
from kubernetes_tpu.store.mvcc import Event, ListResult
from kubernetes_tpu.store.sharded import (
    PARTITIONED_RESOURCES,
    _name_of_key,
    _sort_key,
    multiplex_watches,
    shard_of,
)

import asyncio


class ProcessShardedStore:
    """S `WireStore` clients behind the MVCCStore public surface."""

    def __init__(self, targets: list[str], *, enc: str = "msgpack",
                 token: str | None = None,
                 user_agent: str = "kubernetes-tpu-multiproc"):
        from kubernetes_tpu.apiserver.wire import WireStore
        if not targets:
            raise ValueError("ProcessShardedStore needs >= 1 shard target")
        self.targets = list(targets)
        self.node_shards = len(self.targets)
        self.wires: list = [
            WireStore(t, enc=enc, token=token, user_agent=user_agent)
            for t in self.targets]
        self.meta = self.wires[0]
        self.partitioned_resources = PARTITIONED_RESOURCES
        #: client-side watch accounting (the server-side counters live
        #: in each shard process; pull them via control_stats()).
        self.watch_metrics = WatchMetrics()
        #: no client-side cache tier — getattr(backing, "cacher", None)
        #: consumers read None, same as a cacher-disabled store.
        self.cacher = None

    # -- routing (identical table to ShardedNodeStore) ---------------------

    def shard_index(self, resource: str, name: str) -> int:
        if resource not in self.partitioned_resources:
            return 0
        return shard_of(name, self.node_shards)

    def _wire_for(self, resource: str, name: str):
        return self.wires[self.shard_index(resource, name)]

    def _wire_for_key(self, resource: str, key: str):
        return self._wire_for(resource, _name_of_key(key))

    def _wire_for_obj(self, resource: str, obj: Mapping):
        name = (obj.get("metadata") or {}).get("name", "")
        return self._wire_for(resource, name)

    # -- CRUD (routed) -----------------------------------------------------

    async def create(self, resource: str, obj: Mapping, **kw) -> dict:
        return await self._wire_for_obj(resource, obj).create(
            resource, obj, **kw)

    async def get(self, resource: str, key: str) -> dict:
        return await self._wire_for_key(resource, key).get(resource, key)

    async def update(self, resource: str, obj: Mapping, **kw) -> dict:
        return await self._wire_for_obj(resource, obj).update(
            resource, obj, **kw)

    async def delete(self, resource: str, key: str, *,
                     uid: str | None = None) -> dict:
        return await self._wire_for_key(resource, key).delete(
            resource, key, uid=uid)

    async def apply(self, resource: str, obj: Mapping, *,
                    field_manager: str, force: bool = False) -> dict:
        return await self._wire_for_obj(resource, obj).apply(
            resource, obj, field_manager=field_manager, force=force)

    async def subresource(self, resource: str, key: str, sub: str,
                          body: Mapping) -> dict:
        return await self._wire_for_key(resource, key).subresource(
            resource, key, sub, body)

    async def guaranteed_update(self, resource: str, key: str,
                                mutate: Callable[[dict], dict | None],
                                max_retries: int = 16,
                                return_copy: bool = True):
        return await self._wire_for_key(resource, key).guaranteed_update(
            resource, key, mutate, max_retries=max_retries,
            return_copy=return_copy)

    # -- LIST (merged or shard-scoped) -------------------------------------

    async def list(
        self,
        resource: str,
        namespace: str | None = None,
        selector: Selector | None = None,
        limit: int = 0,
        continue_key: str | None = None,
        fields: Mapping[str, str] | None = None,
        *,
        resource_version: int | None = None,
        resource_version_match: str | None = None,
        shard: int | None = None,
        **_kw,
    ) -> ListResult:
        kw: dict[str, Any] = dict(
            resource_version=resource_version,
            resource_version_match=resource_version_match)
        if resource not in self.partitioned_resources:
            return await self.meta.list(
                resource, namespace, selector, limit, continue_key,
                fields, **kw)
        if shard is not None:
            return await self.wires[self._check_shard(shard)].list(
                resource, namespace, selector, limit, continue_key,
                fields, **kw)
        # Concurrent fan-out over real sockets: each shard's page is
        # individually consistent; the merged RV is the max, so a
        # watch resumed from it can't miss an event (see module doc).
        results = await asyncio.gather(*(
            w.list(resource, namespace, selector, limit, continue_key,
                   fields, **kw)
            for w in self.wires))
        items = [it for lst in results for it in lst.items]
        items.sort(key=_sort_key)
        rv = max(r.resource_version for r in results)
        cont = None
        if limit and len(items) >= limit:
            items = items[:limit]
            from kubernetes_tpu.store.cacher import make_continue
            cont = make_continue(rv, _sort_key(items[-1]))
        return ListResult(items=items, resource_version=rv, cont=cont)

    def _check_shard(self, shard: int) -> int:
        from kubernetes_tpu.store.mvcc import Invalid
        s = int(shard)
        if not 0 <= s < self.node_shards:
            raise Invalid(
                f"shard {s} out of range (store has {self.node_shards})")
        return s

    # -- WATCH (per-shard or multiplexed) ----------------------------------

    async def watch(
        self,
        resource: str,
        resource_version: int = 0,
        namespace: str | None = None,
        selector: Selector | None = None,
        *,
        fields: Mapping[str, str] | None = None,
        bookmarks: bool = True,
        shard: int | None = None,
        **_kw,
    ) -> AsyncIterator[Event]:
        if resource not in self.partitioned_resources:
            return await self.meta.watch(
                resource, resource_version, namespace, selector,
                fields=fields)
        if shard is not None:
            return await self.wires[self._check_shard(shard)].watch(
                resource, resource_version, namespace, selector,
                fields=fields)
        watches = [await w.watch(resource, resource_version, namespace,
                                 selector, fields=fields)
                   for w in self.wires]
        return multiplex_watches(watches, bookmarks)

    # -- discovery ---------------------------------------------------------

    async def control_topology(self) -> dict:
        """The facade IS the topology: clients of a ProcessShardedStore
        are already talking to every shard process, so this answers
        locally instead of probing (each shard server is a plain
        unsharded store and would report nodeShards=1)."""
        return {"nodeShards": self.node_shards,
                "partitioned": list(self.partitioned_resources)}

    async def control_stats(self) -> dict:
        """Per-shard server-side counters (WAL appends/replays, RV),
        merged: sums under "total", the raw rows under "shards"."""
        rows = await asyncio.gather(
            *(w.control_stats() for w in self.wires))
        total: dict[str, float] = {}
        for row in rows:
            for k, v in row.items():
                if k != "shard" and isinstance(v, (int, float)):
                    total[k] = total.get(k, 0) + v
        return {"total": total, "shards": list(rows)}

    def is_cluster_scoped(self, resource: str) -> bool:
        return self.meta.is_cluster_scoped(resource)

    def resource_for_kind(self, kind: str) -> str | None:
        return self.meta.resource_for_kind(kind)

    def kind_map(self) -> dict[str, str]:
        return self.meta.kind_map()

    # -- lifecycle ---------------------------------------------------------

    async def close(self) -> None:
        for w in self.wires:
            await w.close()

    def stop(self) -> None:
        for w in self.wires:
            w.stop()
