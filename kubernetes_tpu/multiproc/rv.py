"""Cross-process ResourceVersion allocation.

The in-process sharded store (store/sharded.py) keeps RVs globally
monotonic by handing ONE `RVCounter` object to every shard. When each
shard becomes its own OS process that object can't be shared by
reference anymore — this module replaces it with a counter over a
`multiprocessing.Value("q")` in shared memory, so allocation stays a
single atomic increment (no allocator process, no RPC on the commit
path) and the contract the single counter gave us survives:

- a merged LIST's RV is resumable on any shard's watch,
- pinned continue tokens address one global snapshot on every shard,
- per-key event order any watcher observes is cluster-wide commit order.

`SharedRVCounter` is duck-compatible with `RVCounter` (`next()`, a
mutable `.value`) so `MVCCStore(rv_source=...)` takes it unchanged.
The one semantic addition: the `.value` SETTER is monotonic (max).
A recovering shard calls `MVCCStore.load()` / WAL replay, which
assigns the snapshot's RV — under a shared counter that assignment
must never roll the cluster-wide clock back past RVs other shards
already handed out.
"""

from __future__ import annotations

import multiprocessing
import multiprocessing.sharedctypes


class SharedRVCounter:
    """`RVCounter` over shared memory: one atomic int64 across every
    control-plane process. Picklable through the spawn channel (the
    synchronized Value rides `multiprocessing.Process` args)."""

    __slots__ = ("_shared",)

    def __init__(self, shared=None, *, ctx=None):
        if shared is None:
            ctx = ctx or multiprocessing.get_context("spawn")
            shared = ctx.Value("q", 0)
        self._shared = shared

    def next(self) -> int:
        with self._shared.get_lock():
            self._shared.value += 1
            return self._shared.value

    @property
    def value(self) -> int:
        with self._shared.get_lock():
            return self._shared.value

    @value.setter
    def value(self, v: int) -> None:
        # Monotonic: recovery (snapshot load, WAL replay) fast-forwards
        # the global clock to at least its own high-water mark but can
        # never regress RVs other shards already allocated.
        v = int(v)
        with self._shared.get_lock():
            if v > self._shared.value:
                self._shared.value = v
