"""Process supervisor for the multi-process control plane.

`MultiProcessControlPlane` owns the OS-process topology ISSUE r22's
tentpole describes: S shard apiserver processes (shardproc.py), an
active/standby scheduler pair (schedproc.py), one shared-memory RV
counter (rv.py), and the unix-socket rendezvous directory. The
parent builds clients with `client()` — a `ProcessShardedStore`
routing over the shard sockets — and drives faults with
`kill_shard` / `restart_shard` / `kill_leader` (SIGKILL, the honest
crash: no atexit, no final snapshot; recovery is snapshot + WAL
replay and lease expiry, not cooperation).

Spawn (not fork) context throughout: children boot clean
interpreters, so a jax-initialized parent never forks a CUDA/TPU
runtime handle into a shard process.
"""

from __future__ import annotations

import asyncio
import multiprocessing
import os
import shutil
import tempfile
import time

from kubernetes_tpu.multiproc.rv import SharedRVCounter
from kubernetes_tpu.multiproc.schedproc import MARKER_KEY, STATUS_KEY, sched_main
from kubernetes_tpu.multiproc.shardproc import shard_main

_READY_TIMEOUT_S = 60.0
_READY_POLL_S = 0.05
_JOIN_TIMEOUT_S = 10.0

#: environment keys shipped to children explicitly (spawn inherits the
#: parent environment anyway; the explicit copy also carries values a
#: flags.scoped_set put in place after interpreter start).
_ENV_PREFIXES = ("KTPU_", "JAX_", "XLA_")


def _child_env() -> dict:
    return {k: v for k, v in os.environ.items()
            if k.startswith(_ENV_PREFIXES)}


class MultiProcessControlPlane:
    def __init__(self, processes: int, *, data_dir: str | None = None,
                 socket_dir: str | None = None,
                 backend_spec: dict | None = None,
                 batch_size: int = 1,
                 scheduler_kwargs: dict | None = None):
        self.processes = max(1, int(processes))
        self.data_dir = data_dir
        self.backend_spec = backend_spec
        self.batch_size = batch_size
        self.scheduler_kwargs = dict(scheduler_kwargs or {})
        self._ctx = multiprocessing.get_context("spawn")
        self.rv = SharedRVCounter(ctx=self._ctx)
        self._own_socket_dir = socket_dir is None
        self.socket_dir = socket_dir or tempfile.mkdtemp(prefix="ktpu-mp-")
        self.targets = [
            f"unix:{os.path.join(self.socket_dir, f'shard-{i}.sock')}"
            for i in range(self.processes)]
        self.shard_procs: list = [None] * self.processes
        #: identity -> Process for the scheduler replicas.
        self.sched_procs: dict[str, object] = {}
        self._store = None  # supervisor's own client (lease reads)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        """Spawn every shard process, then block until each socket
        accepts a connection (interpreter boot + recovery replay)."""
        await asyncio.gather(*(
            self._spawn_shard(i) for i in range(self.processes)))
        from kubernetes_tpu.multiproc.client import ProcessShardedStore
        self._store = ProcessShardedStore(self.targets)

    async def start_schedulers(self, replicas: int = 2) -> None:
        """Boot the leader-elected scheduler pool (2 = the HA pair).
        Replica order seeds no priority — whoever wins the Lease CAS
        leads; the rest idle as standbys."""
        env = _child_env()
        for i in range(replicas):
            identity = f"ktpu-sched-{i}"
            p = self._ctx.Process(
                target=sched_main,
                args=(identity, self.targets, env, self.backend_spec,
                      self.batch_size, self.scheduler_kwargs),
                name=identity, daemon=True)
            await asyncio.to_thread(p.start)
            self.sched_procs[identity] = p

    def client(self):
        from kubernetes_tpu.multiproc.client import ProcessShardedStore
        return ProcessShardedStore(self.targets)

    async def stop(self) -> None:
        if self._store is not None:
            await self._store.close()
            self._store = None
        # Schedulers down first, THEN shards: a replica outliving its
        # sockets floods stderr with reflector reconnect noise.
        scheds = [p for p in self.sched_procs.values() if p is not None]
        shards = [p for p in self.shard_procs if p is not None]
        self.sched_procs.clear()
        self.shard_procs = [None] * self.processes
        for procs in (scheds, shards):
            for p in procs:
                if p.is_alive():
                    p.terminate()  # SIGTERM: shards take a final snapshot
            await asyncio.to_thread(self._join_or_kill, procs)
        if self._own_socket_dir:
            shutil.rmtree(self.socket_dir, ignore_errors=True)

    @staticmethod
    def _join_or_kill(procs: list) -> None:
        deadline = time.monotonic() + _JOIN_TIMEOUT_S
        for p in procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
            if p.is_alive():
                p.kill()
                p.join(timeout=5.0)

    # -- shard processes ---------------------------------------------------

    def _shard_dir(self, index: int) -> str | None:
        return self.data_dir

    async def _spawn_shard(self, index: int) -> None:
        path = self.targets[index][len("unix:"):]
        p = self._ctx.Process(
            target=shard_main,
            args=(index, path, self.rv, self._shard_dir(index),
                  _child_env()),
            name=f"ktpu-shard-{index}", daemon=True)
        await asyncio.to_thread(p.start)
        self.shard_procs[index] = p
        await self._wait_ready(path, p)

    @staticmethod
    async def _wait_ready(path: str, proc) -> None:
        deadline = time.monotonic() + _READY_TIMEOUT_S
        while time.monotonic() < deadline:
            if not proc.is_alive():
                raise RuntimeError(
                    f"shard process exited during boot "
                    f"(exitcode={proc.exitcode})")
            try:
                _, writer = await asyncio.open_unix_connection(path)
                writer.close()
                try:
                    await writer.wait_closed()
                except Exception:
                    pass
                return
            except OSError:
                await asyncio.sleep(_READY_POLL_S)
        raise TimeoutError(f"shard socket {path} not ready "
                           f"after {_READY_TIMEOUT_S}s")

    async def kill_shard(self, index: int) -> None:
        """SIGKILL a shard apiserver mid-flight: no flush, no final
        snapshot — exactly the crash the WAL exists for."""
        p = self.shard_procs[index]
        if p is None:
            return
        p.kill()
        await asyncio.to_thread(p.join, 10.0)
        self.shard_procs[index] = None

    async def restart_shard(self, index: int) -> None:
        """Respawn a killed shard on the same socket, data dir, and
        shared counter; returns once the socket accepts again (recovery
        replay included). Clients reconnect lazily; their expired
        watches relist — the informer contract."""
        if self.shard_procs[index] is not None:
            await self.kill_shard(index)
        await self._spawn_shard(index)

    # -- scheduler HA ------------------------------------------------------

    async def leader_identity(self) -> str | None:
        from kubernetes_tpu.store.mvcc import StoreError
        if self._store is None:
            return None
        try:
            lease = await self._store.get(
                "leases", "kube-system/ktpu-scheduler")
        except StoreError:
            return None
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        expired = time.time() > (spec.get("renewTime") or 0) + (
            spec.get("leaseDurationSeconds") or 0)
        return None if expired else holder

    async def kill_leader(self) -> str | None:
        """SIGKILL the scheduler replica currently holding the lease
        (mid-renewal, no on_stopped_leading): the standby must notice
        via lease EXPIRY, not a handover. Returns the killed identity,
        or None when no live replica holds the lease."""
        holder = await self.leader_identity()
        p = self.sched_procs.get(holder) if holder else None
        if p is None or not p.is_alive():
            return None
        p.kill()
        await asyncio.to_thread(p.join, 10.0)
        del self.sched_procs[holder]
        return holder


class MeasureProtocol:
    """Parent half of the measure-marker handshake (schedproc.py doc):
    `begin()` before the measured phase, `end()` after — returns the
    leader's status row (exact attempt percentiles over the marked
    window, scheduled count, election count)."""

    def __init__(self, store, *, ack_timeout_s: float = 30.0):
        self.store = store
        self.ack_timeout_s = ack_timeout_s
        self._id = 0

    async def begin(self) -> None:
        await self._put("begin")
        await self._wait_ack()

    async def end(self) -> dict:
        await self._put("end")
        return await self._wait_ack()

    async def status(self) -> dict:
        from kubernetes_tpu.store.mvcc import StoreError
        try:
            return (await self.store.get(
                "configmaps", STATUS_KEY)).get("data") or {}
        except StoreError:
            return {}

    async def _put(self, op: str) -> None:
        from kubernetes_tpu.api.meta import new_object
        from kubernetes_tpu.store.mvcc import NotFound
        self._id += 1
        data = {"id": str(self._id), "op": op}

        def put(obj):
            obj["data"] = data
            return obj

        try:
            await self.store.guaranteed_update("configmaps", MARKER_KEY, put)
        except NotFound:
            cm = new_object("ConfigMap", "ktpu-measure", "kube-system")
            cm["data"] = data
            await self.store.create("configmaps", cm)

    async def _wait_ack(self) -> dict:
        deadline = time.monotonic() + self.ack_timeout_s
        while time.monotonic() < deadline:
            row = await self.status()
            if row.get("ackId") == str(self._id):
                return row
            await asyncio.sleep(0.05)
        # A failover mid-window can eat one marker; measurement
        # degrades to parent-side wall-clock numbers, not an error.
        return {}
