"""Sandboxed admission-expression evaluator — the CEL analog.

Parity target: the expression language ValidatingAdmissionPolicy uses
(`staging/src/k8s.io/apiserver/pkg/admission/plugin/cel`): expressions
over `object`, `oldObject`, `request`, `params` that must be (a) unable
to reach anything outside those values and (b) bounded in cost (the
reference compiles CEL with a per-expression cost limit and interrupts
evaluation when the runtime budget is exhausted).

This is NOT Python `eval` of user text. Compilation has two stages:

1. **Whitelist validation**: the source parses with `ast.parse` and
   every node must belong to a small allowed grammar — no calls beyond
   a fixed function set, no underscored identifiers, no lambdas,
   f-strings, starred/keyword args, `**`, or non-scalar literals.
2. **Safe-rewrite + bytecode compile** (the admission hot path runs
   ~10 policy evaluations per request, so evaluation must be native
   speed, not a tree walk): the validated AST is REWRITTEN so that
   every attribute access, subscript, method call, concatenation, and
   comprehension iteration routes through a budget-ticking helper, then
   compiled once with `compile()`. Evaluation `eval()`s the code object
   under a globals dict containing ONLY the helpers, the safe function
   set, and the declared variables — `__builtins__` is empty.

The sandbox invariants:

- **No attribute escape**: `a.b` compiles to `_get(a, "b", budget)` — a
  *mapping lookup*; `getattr` is never reached, so `object.__class__`
  has no meaning (and underscored names are rejected at stage 1
  anyway). Values are only ever the JSON-shaped data handed in.
- **No names beyond the declared variables** (+ comprehension-bound
  locals): the globals dict is closed, builtins are empty.
- **Bounded cost**: helpers decrement a budget; exhaustion raises
  `BudgetExceeded` (comprehension bombs die in `_iter`). `+` results
  are size-capped; `**` and sequence repetition (`"x" * 10**9`) are
  rejected — `*` compiles to a numbers-only helper.

Functions mirror CEL's small standard library: `has()`, `size()`,
`string()`, `int()`, `double()`, `bool()`, `min`/`max`/`sum`,
`all`/`any` (with generator comprehensions standing in for CEL's
`.all()`/`.exists()` macros), and `startsWith`/`endsWith`/`contains`/
`matches`/`lowerAscii`/`upperAscii` string methods.
"""

from __future__ import annotations

import ast
import re
# collections.abc, not typing: the runtime isinstance checks in the
# budget helpers are the hottest lines of the admission chain, and
# typing.Mapping's __instancecheck__ costs ~20x the abc-cached check.
from collections.abc import Mapping
from typing import Any

#: default per-evaluation step budget (the reference's runtime cost
#: limit analog). A typical policy expression uses < 100 steps.
DEFAULT_BUDGET = 10_000

#: max nodes in one compiled expression (compile-time cost limit).
MAX_NODES = 1_000

#: cap on sequence results built by `+` (string/list concat bombs).
MAX_RESULT_LEN = 1 << 16

#: cap on source length for `matches()` regexes and their haystacks.
MAX_REGEX_LEN = 256


class ExpressionError(Exception):
    """Compile- or eval-time failure of a policy expression."""


class BudgetExceeded(ExpressionError):
    """Evaluation ran past its cost budget."""


_MISSING = object()  # has()-tolerated absent-key sentinel

_ALLOWED_NODES = (
    ast.Expression,
    # logic
    ast.BoolOp, ast.And, ast.Or, ast.UnaryOp, ast.Not, ast.USub,
    ast.IfExp,
    # arithmetic (no Pow, no bit ops, no MatMult)
    ast.BinOp, ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod,
    # comparison
    ast.Compare, ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
    ast.In, ast.NotIn,
    # data access + literals
    ast.Constant, ast.Name, ast.Load, ast.Attribute, ast.Subscript,
    ast.List, ast.Tuple, ast.Dict,
    # calls + comprehensions (CEL macro analogs)
    ast.Call, ast.GeneratorExp, ast.ListComp, ast.comprehension,
    ast.Store,
)

#: global functions callable by bare name (safe impls in _BASE_ENV).
_FUNCS = ("has", "size", "string", "int", "double", "bool",
          "min", "max", "sum", "all", "any")

#: whitelisted string "methods" (CEL's string functions).
_STR_METHODS = ("startsWith", "endsWith", "contains", "matches",
                "lowerAscii", "upperAscii")


def compile_expression(source: str) -> "CompiledExpression":
    """Whitelist-validate, safe-rewrite, and bytecode-compile one
    expression. Raises ExpressionError for anything outside the
    sandboxed grammar."""
    if not isinstance(source, str) or not source.strip():
        raise ExpressionError("empty expression")
    try:
        tree = ast.parse(source, mode="eval")
    except (SyntaxError, ValueError, MemoryError, RecursionError) as e:
        raise ExpressionError(f"cannot parse expression: {e}") from e
    count = 0
    for node in ast.walk(tree):
        count += 1
        if count > MAX_NODES:
            raise ExpressionError("expression too large")
        if not isinstance(node, _ALLOWED_NODES):
            raise ExpressionError(
                f"forbidden syntax: {type(node).__name__}")
        if isinstance(node, ast.Constant) and not isinstance(
                node.value, (str, int, float, bool, type(None))):
            raise ExpressionError(
                f"forbidden literal: {type(node.value).__name__}")
        if isinstance(node, ast.Dict) and None in node.keys:
            raise ExpressionError("dict unpacking is forbidden")
        if isinstance(node, (ast.Name, ast.Attribute)):
            ident = node.id if isinstance(node, ast.Name) else node.attr
            if ident.startswith("_"):
                raise ExpressionError(f"forbidden identifier {ident!r}")
        if isinstance(node, ast.comprehension):
            if node.is_async:
                raise ExpressionError("async comprehension forbidden")
            if not isinstance(node.target, ast.Name):
                raise ExpressionError(
                    "comprehension target must be a simple name")
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name):
                if fn.id not in _FUNCS:
                    raise ExpressionError(f"unknown function {fn.id!r}")
                if fn.id == "has" and (
                        len(node.args) != 1 or not isinstance(
                            node.args[0],
                            (ast.Attribute, ast.Subscript))):
                    raise ExpressionError("has() takes one field path")
            elif isinstance(fn, ast.Attribute):
                if fn.attr not in _STR_METHODS:
                    raise ExpressionError(f"unknown method {fn.attr!r}")
            else:
                raise ExpressionError("computed calls are forbidden")
            if node.keywords:
                raise ExpressionError("keyword arguments are forbidden")
    rewritten = ast.fix_missing_locations(_Rewriter().visit(tree))
    try:
        code = compile(rewritten, "<policy-expression>", "eval")
    except (SyntaxError, ValueError, RecursionError) as e:
        raise ExpressionError(f"cannot compile expression: {e}") from e
    return CompiledExpression(source, code)


class _Rewriter(ast.NodeTransformer):
    """Rewrite the VALIDATED tree so every operation that could escape
    the data model or run unbounded routes through a helper. After this
    pass no raw Attribute/Subscript nodes remain."""

    def _b(self) -> ast.Name:
        return ast.Name(id="_b", ctx=ast.Load())

    def _call(self, helper: str, args: list) -> ast.Call:
        return ast.Call(func=ast.Name(id=helper, ctx=ast.Load()),
                        args=args, keywords=[])

    def visit_Attribute(self, node: ast.Attribute) -> ast.Call:
        return self._call("_get", [self.visit(node.value),
                                   ast.Constant(node.attr), self._b()])

    def visit_Subscript(self, node: ast.Subscript) -> ast.Call:
        return self._call("_idx", [self.visit(node.value),
                                   self.visit(node.slice), self._b()])

    def _tolerant(self, node) -> ast.expr:
        """has()'s field path: absent keys yield _MISSING instead of
        raising, through the whole chain."""
        if isinstance(node, ast.Attribute):
            return self._call("_get_t", [self._tolerant(node.value),
                                         ast.Constant(node.attr),
                                         self._b()])
        if isinstance(node, ast.Subscript):
            return self._call("_idx_t", [self._tolerant(node.value),
                                         self.visit(node.slice),
                                         self._b()])
        return self.visit(node)

    def visit_Call(self, node: ast.Call) -> ast.Call:
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id == "has":
            return self._call("_has", [self._tolerant(node.args[0])])
        if isinstance(fn, ast.Attribute):
            # whitelisted string method → _meth(recv, name, args, _b)
            return self._call("_meth", [
                self.visit(fn.value), ast.Constant(fn.attr),
                ast.Tuple(elts=[self.visit(a) for a in node.args],
                          ctx=ast.Load()),
                self._b()])
        return self._call(fn.id, [self.visit(a) for a in node.args])

    def visit_BinOp(self, node: ast.BinOp) -> ast.expr:
        left, right = self.visit(node.left), self.visit(node.right)
        if isinstance(node.op, ast.Add):
            return self._call("_add", [left, right])
        if isinstance(node.op, ast.Mult):
            return self._call("_mul", [left, right])
        if isinstance(node.op, ast.Mod):
            # native % on a str left operand is printf formatting — a
            # "%09999999d" constant would be a memory bomb.
            return self._call("_mod", [left, right])
        return ast.BinOp(left=left, op=node.op, right=right)

    def _wrap_comp(self, node):
        self.generic_visit(node)
        for gen in node.generators:
            gen.iter = self._call("_iter", [gen.iter, self._b()])
        return node

    def visit_GeneratorExp(self, node):
        return self._wrap_comp(node)

    def visit_ListComp(self, node):
        return self._wrap_comp(node)


# ---------------------------------------------------------------------------
# runtime helpers (the only callables reachable from compiled code)
# ---------------------------------------------------------------------------

_BUDGET_MSG = "expression cost budget exceeded"


def _get(base: Any, attr: str, b: list) -> Any:
    # budget tick inlined (this is the hottest helper: one call per
    # field access, ~10 policy evaluations per admitted request); the
    # `type is dict` check short-circuits the abc isinstance — nearly
    # every value here is a plain JSON dict.
    b[0] -= 1
    if b[0] < 0:
        raise BudgetExceeded(_BUDGET_MSG)
    if type(base) is not dict and not isinstance(base, Mapping):
        raise ExpressionError(
            f"field access {attr!r} on non-object "
            f"{type(base).__name__}")
    if attr in base:
        return base[attr]
    raise ExpressionError(f"no such field {attr!r}")


def _get_t(base: Any, attr: str, b: list) -> Any:
    b[0] -= 1
    if b[0] < 0:
        raise BudgetExceeded(_BUDGET_MSG)
    if type(base) is not dict and (
            base is _MISSING or not isinstance(base, Mapping)):
        return _MISSING
    return base[attr] if attr in base else _MISSING


def _idx(base: Any, idx: Any, b: list) -> Any:
    b[0] -= 1
    if b[0] < 0:
        raise BudgetExceeded(_BUDGET_MSG)
    if type(base) is dict or isinstance(base, Mapping):
        if idx in base:
            return base[idx]
        raise ExpressionError(f"no such key {idx!r}")
    if isinstance(base, (list, tuple, str)) and \
            isinstance(idx, int) and not isinstance(idx, bool):
        try:
            return base[idx]
        except IndexError:
            raise ExpressionError(f"index {idx!r} out of range") \
                from None
    raise ExpressionError(
        f"cannot index {type(base).__name__} with {idx!r}")


def _idx_t(base: Any, idx: Any, b: list) -> Any:
    if base is _MISSING:
        return _MISSING
    try:
        return _idx(base, idx, b)
    except BudgetExceeded:
        raise
    except ExpressionError:
        return _MISSING


def _has(v: Any) -> bool:
    return v is not _MISSING


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _add(lhs: Any, rhs: Any) -> Any:
    if isinstance(lhs, str) and isinstance(rhs, str):
        if len(lhs) + len(rhs) > MAX_RESULT_LEN:
            raise BudgetExceeded("string result too large")
        return lhs + rhs
    if isinstance(lhs, list) and isinstance(rhs, list):
        if len(lhs) + len(rhs) > MAX_RESULT_LEN:
            raise BudgetExceeded("list result too large")
        return lhs + rhs
    if _is_num(lhs) and _is_num(rhs):
        return lhs + rhs
    raise ExpressionError(
        f"cannot add {type(lhs).__name__} and {type(rhs).__name__}")


def _mul(lhs: Any, rhs: Any) -> Any:
    # Numbers only: sequence repetition is a memory bomb, and CEL has
    # no such operator either.
    if _is_num(lhs) and _is_num(rhs):
        return lhs * rhs
    raise ExpressionError("operator needs numbers, got "
                          f"{type(lhs).__name__} and "
                          f"{type(rhs).__name__}")


def _mod(lhs: Any, rhs: Any) -> Any:
    if _is_num(lhs) and _is_num(rhs):
        try:
            return lhs % rhs
        except ZeroDivisionError:
            raise ExpressionError("division by zero") from None
    raise ExpressionError("operator needs numbers, got "
                          f"{type(lhs).__name__} and "
                          f"{type(rhs).__name__}")


def _iter(src: Any, b: list):
    if not isinstance(src, (list, tuple)):
        raise ExpressionError("comprehension needs a list")
    for item in src:
        b[0] -= 1
        if b[0] < 0:
            raise BudgetExceeded(_BUDGET_MSG)
        yield item


def _meth(recv: Any, name: str, args: tuple, b: list) -> Any:
    b[0] -= 1
    if b[0] < 0:
        raise BudgetExceeded(_BUDGET_MSG)
    if not isinstance(recv, str):
        raise ExpressionError(
            f"{name}() needs a string receiver, got "
            f"{type(recv).__name__}")
    if name in ("lowerAscii", "upperAscii"):
        _arity(name, args, 0)
        return recv.lower() if name == "lowerAscii" else recv.upper()
    (arg,) = _arity(name, args, 1)
    if not isinstance(arg, str):
        raise ExpressionError(f"{name}() needs a string argument")
    if name == "startsWith":
        return recv.startswith(arg)
    if name == "endsWith":
        return recv.endswith(arg)
    if name == "contains":
        return arg in recv
    # matches: bounded regex — cap pattern + haystack size so
    # catastrophic backtracking can't stall the apiserver.
    if len(arg) > MAX_REGEX_LEN or len(recv) > MAX_REGEX_LEN * 16:
        raise BudgetExceeded("matches() input too large")
    try:
        return re.search(arg, recv) is not None
    except re.error as e:
        raise ExpressionError(f"bad regex: {e}") from None


def _arity(name: str, args, n: int):
    if len(args) != n:
        raise ExpressionError(f"{name}() takes {n} argument(s), "
                              f"got {len(args)}")
    return args


def _fn_size(v: Any) -> int:
    if isinstance(v, (str, list, tuple, dict)):
        return len(v)
    raise ExpressionError("size() needs a string/list/map")


def _fn_string(v: Any) -> str:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return "" if v is None else str(v)
    raise ExpressionError("string() needs a scalar")


def _fn_int(v: Any) -> int:
    try:
        return int(v)
    except (TypeError, ValueError) as e:
        raise ExpressionError(f"int(): {e}") from None


def _fn_double(v: Any) -> float:
    try:
        return float(v)
    except (TypeError, ValueError) as e:
        raise ExpressionError(f"double(): {e}") from None


def _agg(name: str, native):
    def fn(*args):
        if len(args) == 1 and isinstance(args[0], (list, tuple)):
            args = tuple(args[0])
        if not args:
            raise ExpressionError(f"{name}() of empty sequence")
        if not all(_is_num(a) for a in args):
            raise ExpressionError(f"{name}() needs numbers")
        return native(args)
    return fn


def _pred(name: str, native):
    def fn(v):
        if isinstance(v, (str, Mapping)) or not hasattr(v, "__iter__"):
            raise ExpressionError(f"{name}() needs a list")
        return native(bool(x) for x in v)
    return fn


#: the closed globals every compiled expression runs under. Helpers are
#: underscore-named — unreachable from source (stage-1 rejects
#: underscored identifiers) but emitted by the rewriter.
_BASE_ENV = {
    "__builtins__": {},
    "_get": _get, "_get_t": _get_t, "_idx": _idx, "_idx_t": _idx_t,
    "_has": _has, "_add": _add, "_mul": _mul, "_mod": _mod,
    "_iter": _iter, "_meth": _meth,
    "size": _fn_size, "string": _fn_string, "int": _fn_int,
    "double": _fn_double, "bool": bool,
    "min": _agg("min", min), "max": _agg("max", max),
    "sum": _agg("sum", sum),
    "all": _pred("all", all), "any": _pred("any", any),
}


def make_env(variables: Mapping[str, Any]) -> dict:
    """Build an evaluation environment once and reuse it across many
    `CompiledExpression.evaluate_env` calls (the admission hot path
    evaluates every bound policy against one request — rebuilding the
    helper dict per expression was measurable). Mutate the returned
    dict's variable entries (e.g. `env["params"] = ...`) between calls."""
    env = dict(_BASE_ENV)
    env.update(variables)
    return env


class CompiledExpression:
    """One validated, safe-rewritten, bytecode-compiled expression,
    reusable across evaluations (policies compile once per
    resourceVersion)."""

    __slots__ = ("source", "_code")

    def __init__(self, source: str, code):
        self.source = source
        self._code = code

    def evaluate_env(self, env: dict,
                     budget: int = DEFAULT_BUDGET) -> Any:
        """Evaluate inside a `make_env` dict (shared across expressions;
        a fresh budget is installed per call). Raises ExpressionError on
        any type/lookup failure, BudgetExceeded past the step budget.

        Everything lives in the GLOBALS dict (not locals) so names
        resolve inside comprehension frames too."""
        env["_b"] = [budget]
        try:
            return eval(self._code, env)  # noqa: S307 — sandboxed code
        except ExpressionError:
            raise
        except NameError as e:
            raise ExpressionError(f"unknown variable: {e}") from None
        except (TypeError, ValueError, KeyError, IndexError,
                ZeroDivisionError, AttributeError, OverflowError,
                RecursionError) as e:
            raise ExpressionError(f"evaluation failed: {e}") from None

    def evaluate_shared(self, env: dict) -> Any:
        """Evaluate inside an ALREADY-BUDGETED env (no fresh budget
        installed): the `variables.<name>` composition path, where a
        lazily-evaluated variable must tick the enclosing expression's
        budget instead of minting its own — a chain of variables cannot
        multiply the per-expression cost limit."""
        try:
            return eval(self._code, env)  # noqa: S307 — sandboxed code
        except ExpressionError:
            raise
        except NameError as e:
            raise ExpressionError(f"unknown variable: {e}") from None
        except (TypeError, ValueError, KeyError, IndexError,
                ZeroDivisionError, AttributeError, OverflowError,
                RecursionError) as e:
            raise ExpressionError(f"evaluation failed: {e}") from None

    def evaluate(self, variables: Mapping[str, Any],
                 budget: int = DEFAULT_BUDGET) -> Any:
        """One-shot convenience over evaluate_env."""
        return self.evaluate_env(make_env(variables), budget)
