"""Policy engine subsystem (SURVEY §3.2 admission + §5.5 audit).

Three layers:

- `expr.py` — the sandboxed expression evaluator (the CEL analog): a
  restricted AST-walk interpreter over `object` / `oldObject` /
  `request` / `params` with a hard cost budget and no path to Python
  attributes, imports, or builtins.
- `vap.py` — ValidatingAdmissionPolicy + ValidatingAdmissionPolicyBinding
  evaluation (`admissionregistration.k8s.io` shapes as stored resources),
  consumed by `apiserver/admission.py` before validating webhooks.
- `audit.py` — the policy-driven audit pipeline (levels
  None|Metadata|Request|RequestResponse, RequestReceived →
  ResponseComplete stage events, bounded async JSON sink), registered on
  both wires plus the gRPC interceptor chain.
"""

from kubernetes_tpu.policy.audit import (  # noqa: F401
    AuditPipeline,
    AuditPolicy,
    AuditSink,
    LEVEL_METADATA,
    LEVEL_NONE,
    LEVEL_REQUEST,
    LEVEL_REQUEST_RESPONSE,
)
from kubernetes_tpu.policy.expr import (  # noqa: F401
    BudgetExceeded,
    CompiledExpression,
    ExpressionError,
    compile_expression,
)
from kubernetes_tpu.policy.vap import (  # noqa: F401
    PolicyDenied,
    PolicyEngine,
)
