"""ValidatingAdmissionPolicy evaluation over stored resources.

Parity target: `staging/src/k8s.io/apiserver/pkg/admission/plugin/
policy/validating` — ValidatingAdmissionPolicy + Binding objects
(admissionregistration.k8s.io/v1) stored via the API, evaluated in the
admission chain BEFORE validating webhooks. Shape subset:

    kind: ValidatingAdmissionPolicy
    spec:
      failurePolicy: Fail | Ignore          # default Fail, the reference
      paramKind: {kind: ConfigMap}          # optional params resource
      matchConstraints:
        resourceRules:
        - resources: ["pods"]               # "*" allowed
          operations: ["CREATE", "UPDATE"]  # default "*"
        namespaceSelector: {matchLabels: ...}   # labels of the OBJECT'S
                                                # Namespace (api/labels)
      matchConditions:                      # expression prefilter; ALL
      - name: has-containers                # must hold for the policy
        expression: "has(object.spec.containers)"   # to apply
      variables:                            # composition: lazy, memoized
      - name: cset                          # once per binding evaluation,
        expression: "object.spec.containers"    # read as `variables.cset`
      validations:
      - expression: "object.spec.replicas <= params.data.maxReplicas"
        message: "replica cap"
        messageExpression: "'cap is ' + string(params.data.maxReplicas)"
        reason: Invalid
      auditAnnotations:                     # flow into the audit event as
      - key: owner                          # annotations["<policy>/owner"]
        valueExpression: "object.metadata.labels['team']"

    kind: ValidatingAdmissionPolicyBinding
    spec:
      policyName: replica-cap
      paramRef: {name: cap, namespace: default}   # optional

A policy only runs where a binding selects it (the reference contract);
params resolve via the binding's paramRef against the policy's
paramKind. Expression failures (compile error, missing param, budget
exhaustion, matchCondition/auditAnnotation errors) obey failurePolicy:
Fail denies, Ignore skips — exactly the webhook-unreachable semantics
next door in apiserver/admission.py. On DELETE the reference passes
`object=null` with the stored object as `oldObject` — both wires route
deletes through here with exactly that shape.

**O(matching) dispatch** (the tenant-scale path, SURVEY §3.2/§5.5): a
multi-tenant control plane stores hundreds-to-thousands of policies but
only a handful match any one request, so per-request cost must be
O(matching policies), not O(stored policies). The engine pre-indexes the
active set the way store/mvcc interns watch selectors (r8):

- **exact-key reverse map** over (resource, OPERATION) built from the
  precompiled resourceRules — a bucket lookup replaces the per-policy
  rule scan. Policies with a wildcard resource/operation (or no
  matchConstraints at all) bucket into a linear **residue** list, checked
  per request like today.
- **interned namespace-selector signatures**: distinct selector contents
  get one signature id; `match_label_selector` runs once per (signature,
  namespace) and is memoized across requests, invalidated per-namespace
  by a mutator on namespace label writes. Policies sharing a selector
  share the one evaluation.
- **prebuilt param/binding closures**: paramKind→resource resolution and
  the namespaced key are computed at index build; the per-request
  resolver is a single table `.get`.

The index rebuilds lazily on the existing mutator-invalidation seam (a
policy/binding table write clears the cache, the next admit rebuilds).
`KTPU_POLICY_INDEX=0` structurally degrades candidate selection to the
linear all-entries scan (no index structures are built at all); both
paths share ONE evaluation core, so verdicts are bit-identical by
construction — the differential suite (tests/test_policy_index.py) pins
it anyway.

Metrics: `policy_evaluations_total{policy=}`,
`policy_rejections_total{policy=}`, plus the index accounting
`policy_index_hits_total` (candidates served from the exact map),
`policy_index_residue_scans_total` (residue entries linearly checked)
and `policy_index_rebuilds_total` — the bench detail JSON reports the
measured-phase deltas so a dispatch regression is data.
"""

from __future__ import annotations

import json
import logging
# collections.abc Mapping: _LazyVars rides the expression helpers'
# isinstance(base, Mapping) hot path — the abc-cached check, not
# typing's slow __instancecheck__.
from collections.abc import Mapping
from typing import Any, Callable

from kubernetes_tpu.api.labels import match_label_selector
from kubernetes_tpu.api.meta import name_of, namespace_of
from kubernetes_tpu.metrics.registry import Registry
from kubernetes_tpu.policy.expr import (
    CompiledExpression,
    ExpressionError,
    compile_expression,
    make_env,
)
from kubernetes_tpu.store.mvcc import Invalid
from kubernetes_tpu.utils import flags

logger = logging.getLogger(__name__)

POLICY_RESOURCE = "validatingadmissionpolicies"
BINDING_RESOURCE = "validatingadmissionpolicybindings"


class PolicyDenied(Invalid):
    """A validation expression evaluated false (or failed with
    failurePolicy=Fail). Maps to 422/Invalid on both wires, carrying the
    policy's message in the returned Status."""


def _compile_or_error(source: str):
    try:
        return compile_expression(source)
    except ExpressionError as e:
        return e


class _LazyVars(Mapping):
    """`variables.<name>` composition: each variable evaluates lazily on
    first access and memoizes for the rest of the current binding's
    evaluation (the reference's lazy CEL variable composition; a fresh
    memo per binding keeps params-referencing variables honest when
    bindings carry different params). Evaluation shares the enclosing
    expression's environment AND cost budget, so a variable chain
    cannot multiply the per-expression budget."""

    __slots__ = ("_compiled", "_env", "_memo")

    def __init__(self, compiled: Mapping[str, Any], env: dict):
        self._compiled = compiled
        self._env = env
        self._memo: dict[str, Any] = {}

    def __getitem__(self, name: str) -> Any:
        if name in self._memo:
            return self._memo[name]
        c = self._compiled.get(name)
        if c is None:
            raise ExpressionError(f"no such variable {name!r}")
        if isinstance(c, ExpressionError):
            raise c
        value = c.evaluate_shared(self._env)
        self._memo[name] = value
        return value

    def __contains__(self, name) -> bool:
        return name in self._compiled

    def __iter__(self):
        return iter(self._compiled)

    def __len__(self) -> int:
        return len(self._compiled)


_NO_VARS: Mapping[str, Any] = {}


class _Entry:
    """One bound policy, fully precompiled for the admission hot path."""

    __slots__ = ("policy", "pname", "fail_closed", "bindings",
                 "validations", "conditions", "variables", "annotations",
                 "rule_sets", "ns_sel", "ns_sig", "ckey", "seq")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class PolicyEngine:
    """Evaluates the stored VAP set for one (object, resource, op).

    Reads policies/bindings live from the store tables (the reference
    watches them via informers; in-process tables are the same freshness
    for free), precompiles them into `_Entry` records on the
    mutator-invalidation seam, and dispatches per request through the
    (resource, operation) exact-key index — or the linear entry scan
    under `KTPU_POLICY_INDEX=0`."""

    def __init__(self, store, registry: Registry | None = None):
        self.store = store
        r = registry or Registry()
        self.registry = r
        self.evaluations = r.counter(
            "policy_evaluations_total",
            "ValidatingAdmissionPolicy expressions evaluated",
            labels=("policy",))
        self.rejections = r.counter(
            "policy_rejections_total",
            "Requests denied by a ValidatingAdmissionPolicy",
            labels=("policy",))
        self.index_hits = r.counter(
            "policy_index_hits_total",
            "Policy candidates dispatched from the (resource, "
            "operation) exact-key index after the namespace-signature "
            "prefilter")
        self.index_residue_scans = r.counter(
            "policy_index_residue_scans_total",
            "Residue (wildcard/unconstrained) policy entries linearly "
            "checked per request")
        self.index_rebuilds = r.counter(
            "policy_index_rebuilds_total",
            "Policy index rebuilds after a policy/binding table write")
        #: policy name -> (resourceVersion, compiled bundle) — compile
        #: once per (name, rv); entries are CompiledExpression or the
        #: ExpressionError the compile raised (so a broken expression
        #: keeps obeying failurePolicy instead of recompiling per
        #: request).
        self._compiled: dict[str, tuple[str, tuple]] = {}
        #: prebuilt [_Entry] in store-table order for the admission hot
        #: path, invalidated by store mutators on the two policy tables
        #: (O(1) per write, zero rescans per admit).
        self._active: list | None = None
        #: ({(resource, OP): [(sig id | None, [_Entry])]},
        #: [residue _Entry]) — exact-key buckets GROUPED by interned
        #: namespace-selector signature, so one memoized signature check
        #: admits or rejects a whole tenant's worth of policies. Built
        #: lazily from `_active` on the first indexed dispatch; stays
        #: None under KTPU_POLICY_INDEX=0 (the structural-degrade
        #: witness).
        self._index: tuple | None = None
        #: namespace-selector signature interning: canonical selector
        #: JSON -> stable id, shared by every policy carrying that
        #: selector content; _sig_sel maps the id back to one
        #: representative selector dict for evaluation.
        self._sig_ids: dict[str, int] = {}
        self._sig_sel: dict[int, Mapping] = {}
        #: namespace -> {signature id: matched} — one selector eval per
        #: (signature, namespace), reused across requests. Invalidated
        #: per-namespace on namespace writes (the mutator below).
        self._ns_memo: dict[str, dict[int, bool]] = {}
        #: namespace -> {(resource, OP): (candidates, n_exact,
        #: n_residue)} — the fully-resolved candidate list per request
        #: shape. Steady-state dispatch is two dict lookups; the memo
        #: shares both invalidation seams (policy writes clear it with
        #: the index, namespace writes pop their one key).
        self._cand_memo: dict[str, dict[tuple[str, str], tuple]] = {}

        def invalidate(_obj, _self=self):
            _self._active = None
            _self._index = None
            _self._cand_memo.clear()

        for table in (POLICY_RESOURCE, BINDING_RESOURCE):
            store.register_mutator(
                table, invalidate, on=("create", "update", "delete"))

        def invalidate_ns(obj, _self=self):
            ns = name_of(obj)
            _self._ns_memo.pop(ns, None)
            _self._cand_memo.pop(ns, None)

        store.register_mutator(
            "namespaces", invalidate_ns,
            on=("create", "update", "delete"))

    def register_into(self, registry: Registry) -> None:
        """Surface the counters through another registry's render (the
        WatchMetrics pattern — same Counter objects, one truth)."""
        for c in (self.evaluations, self.rejections, self.index_hits,
                  self.index_residue_scans, self.index_rebuilds):
            registry._metrics.setdefault(c.name, c)

    # -- store access ------------------------------------------------------

    def _bindings_for(self, policy_name: str) -> list[dict]:
        return [b for b in self.store._table(BINDING_RESOURCE).values()
                if (b.get("spec") or {}).get("policyName") == policy_name]

    def _compiled_policy(self, policy: Mapping) -> tuple:
        """(validations, conditions, variables, annotations), each
        precompiled, cached per (name, rv)."""
        name = name_of(policy)
        rv = policy.get("metadata", {}).get("resourceVersion", "")
        cached = self._compiled.get(name)
        if cached is not None and cached[0] == rv:
            return cached[1]
        spec = policy.get("spec") or {}
        validations = []
        for v in spec.get("validations") or []:
            msg_expr = None
            if v.get("messageExpression"):
                msg_expr = _compile_or_error(v["messageExpression"])
            validations.append((
                _compile_or_error(v.get("expression", "")),
                v.get("message", ""), msg_expr))
        conditions = [
            (c.get("name", ""), _compile_or_error(c.get("expression", "")))
            for c in spec.get("matchConditions") or []]
        variables = {
            var.get("name", ""):
                _compile_or_error(var.get("expression", ""))
            for var in spec.get("variables") or []}
        annotations = [
            (a.get("key", ""),
             _compile_or_error(a.get("valueExpression", "")))
            for a in spec.get("auditAnnotations") or []]
        bundle = (validations, conditions, variables, annotations)
        self._compiled[name] = (rv, bundle)
        return bundle

    def _namespace_labels(self, namespace: str) -> Mapping[str, str]:
        ns_obj = self.store._table("namespaces").get(namespace)
        if ns_obj is None:
            return {}
        return ns_obj.get("metadata", {}).get("labels") or {}

    def _param_resolver(self, policy: Mapping,
                        binding: Mapping) -> Callable[[], Any]:
        """Prebuild paramRef → stored-object resolution: kind→resource
        and the namespaced key resolve ONCE at index build, the
        per-request call is a single table `.get`. Raises
        ExpressionError when a configured param is missing — subject to
        failurePolicy, like the reference's paramNotFoundAction
        default."""
        param_kind = ((policy.get("spec") or {}).get("paramKind")
                      or {}).get("kind")
        ref = (binding.get("spec") or {}).get("paramRef") or {}
        if not param_kind or not ref.get("name"):
            return lambda: None
        resource = self.store.resource_for_kind(param_kind)
        if resource is None:
            err = ExpressionError(
                f"paramKind {param_kind!r} has no known resource")

            def unknown_kind(_err=err):
                raise _err
            return unknown_kind
        if self.store.is_cluster_scoped(resource):
            key = ref["name"]
        else:
            # A namespaced paramKind always needs a namespaced key — an
            # omitted paramRef.namespace defaults rather than building a
            # bare key that can never match (which, under
            # failurePolicy=Fail, would deny every request).
            key = f"{ref.get('namespace') or 'default'}/{ref['name']}"

        def resolve(_store=self.store, _resource=resource, _key=key,
                    _kind=param_kind):
            params = _store._table(_resource).get(_key)
            if params is None:
                raise ExpressionError(
                    f"param {_kind} {_key!r} not found")
            return params
        return resolve

    # -- active set + index ------------------------------------------------

    def _active_set(self) -> list:
        """One prebuilt `_Entry` per bound policy, in store-table order —
        rebuilt only after a policy/binding table write (the mutators
        above clear it). resourceRules precompile to frozenset pairs,
        expressions compile once per (name, rv), param resolution and
        counter label tuples precompute."""
        active = self._active
        if active is None:
            active = []
            # Re-intern from scratch: under policy churn with varying
            # selector contents the signature tables would otherwise
            # grow without bound (and _ns_memo would keep booleans for
            # dead ids). Rebuilds are policy-write-rare; the memo
            # refills on the next requests.
            self._sig_ids = {}
            self._sig_sel = {}
            self._ns_memo.clear()
            for policy in self.store._table(POLICY_RESOURCE).values():
                pname = name_of(policy)
                bindings = self._bindings_for(pname)
                if not bindings:
                    continue  # unbound policies are inert (reference)
                spec = policy.get("spec") or {}
                constraints = spec.get("matchConstraints") or {}
                rule_sets = None  # None = match everything (reference)
                if constraints.get("resourceRules"):
                    rule_sets = [
                        (frozenset(rule.get("resources") or ()),
                         frozenset(str(o).upper() for o in
                                   rule.get("operations") or ["*"]))
                        for rule in constraints["resourceRules"]]
                validations, conditions, variables, annotations = \
                    self._compiled_policy(policy)
                ns_sel = constraints.get("namespaceSelector")
                ns_sig = None
                if ns_sel is not None:
                    sig_key = json.dumps(ns_sel, sort_keys=True,
                                         separators=(",", ":"))
                    ns_sig = self._sig_ids.setdefault(
                        sig_key, len(self._sig_ids))
                    self._sig_sel.setdefault(ns_sig, ns_sel)
                active.append(_Entry(
                    policy=policy, pname=pname,
                    fail_closed=spec.get("failurePolicy",
                                         "Fail") != "Ignore",
                    bindings=[(b, self._param_resolver(policy, b))
                              for b in bindings],
                    validations=validations, conditions=conditions,
                    variables=variables, annotations=annotations,
                    rule_sets=rule_sets, ns_sel=ns_sel, ns_sig=ns_sig,
                    ckey=(pname,), seq=len(active)))
            self._active = active
        return active

    def _build_index(self, entries: list) -> tuple:
        """(exact {(resource, OP): [(sig, [entry])]}, residue [entry]):
        entries whose every rule is concrete land in the exact map under
        each (resource, operation) pair, grouped by namespace-selector
        signature — the per-request cost of a bucket is one memoized
        signature check per DISTINCT selector, not one per policy.
        Anything with a wildcard — or no matchConstraints — stays
        linear in the residue."""
        raw: dict[tuple[str, str], dict] = {}
        residue: list = []
        for entry in entries:
            if entry.rule_sets is None or any(
                    "*" in rs or "*" in ops
                    for rs, ops in entry.rule_sets):
                residue.append(entry)
                continue
            sig = entry.ns_sig if entry.ns_sel is not None else None
            for rs, ops in entry.rule_sets:
                for resource in rs:
                    for op in ops:
                        group = raw.setdefault(
                            (resource, op), {}).setdefault(sig, [])
                        # one rule set may repeat a pair; keep one copy
                        if not group or group[-1] is not entry:
                            group.append(entry)
        exact = {key: list(groups.items()) for key, groups in raw.items()}
        self._cand_memo.clear()  # resolved lists referenced old groups
        self._index = (exact, residue)
        self.index_rebuilds.inc()
        return self._index

    @staticmethod
    def _rules_match(entry, resource: str, op: str) -> bool:
        if entry.rule_sets is None:
            return True
        return any(("*" in rs or resource in rs)
                   and ("*" in ops or op in ops)
                   for rs, ops in entry.rule_sets)

    def _candidates_indexed(self, entries: list, resource: str,
                            op: str, ns: str) -> list:
        """Candidates for one request: the (resource, op) bucket's
        signature groups that pass the memoized namespace check, plus
        the rule/selector-checked residue — merged back into
        store-table order so first-deny verdicts stay bit-identical to
        the linear scan. The resolved list memoizes per (namespace,
        resource, op): steady-state dispatch is two dict lookups."""
        idx = self._index
        if idx is None:
            idx = self._build_index(entries)
        by_key = self._cand_memo.setdefault(ns, {})
        hit = by_key.get((resource, op))
        if hit is None:
            exact, residue = idx
            out_lists = []
            n_cand = 0
            for sig, group in exact.get((resource, op), ()):
                if sig is not None and ns \
                        and not self._sig_match(sig, ns):
                    continue
                out_lists.append(group)
                n_cand += len(group)
            n_residue = len(residue)
            if residue:
                matched = [
                    e for e in residue
                    if self._rules_match(e, resource, op)
                    and not (e.ns_sel is not None and ns
                             and not self._sig_match(e.ns_sig, ns))]
                if matched:
                    out_lists.append(matched)
            if not out_lists:
                cands: list = []
            elif len(out_lists) == 1:
                cands = out_lists[0]
            else:
                cands = [e for lst in out_lists for e in lst]
                cands.sort(key=lambda e: e.seq)
            hit = (cands, n_cand, n_residue)
            by_key[(resource, op)] = hit
        cands, n_cand, n_residue = hit
        # counters move per REQUEST (memo hit or miss): the detail
        # JSON's hits/residue deltas stay a per-request dispatch
        # measure, not a cache-population artifact.
        if n_cand:
            self.index_hits.inc(n_cand)
        if n_residue:
            self.index_residue_scans.inc(n_residue)
        return cands

    def _sig_match(self, sig: int, namespace: str) -> bool:
        """Interned-signature selector check: one match_label_selector
        eval per (signature, namespace), memoized across requests and
        shared by every policy carrying the same selector content."""
        memo = self._ns_memo.setdefault(namespace, {})
        hit = memo.get(sig)
        if hit is None:
            hit = match_label_selector(
                self._sig_sel[sig], self._namespace_labels(namespace))
            memo[sig] = hit
        return hit

    # -- evaluation --------------------------------------------------------

    def validate(self, obj: Mapping | None, resource: str,
                 operation: str, *,
                 old_object: Mapping | None = None,
                 user: str | None = None,
                 groups: list[str] | None = None) -> None:
        """Run every bound, matching policy; raise PolicyDenied on the
        first failing validation (Fail semantics) — Ignore-policy errors
        are logged and skipped. On DELETE the caller passes `obj=None`
        with the stored object as `old_object` (the reference's
        `object=null` contract); namespace/name then derive from the
        old object."""
        entries = self._active_set()
        if not entries:
            return
        op = operation.upper()
        ref = obj if obj is not None else (old_object or {})
        ns = namespace_of(ref)
        use_index = flags.get("KTPU_POLICY_INDEX")
        if use_index:
            cands = self._candidates_indexed(entries, resource, op, ns)
            if not cands:
                return
        else:
            cands = entries
        ns_labels: Mapping[str, str] | None = None
        request = {
            "operation": op,
            "resource": resource,
            "namespace": ns,
            "name": name_of(ref),
            "userInfo": {"username": user or "",
                         "groups": list(groups or [])},
        }
        #: one env shared by every expression this admit evaluates —
        #: only `params`/`variables` vary per entry/binding
        #: (expr.make_env contract).
        env: dict | None = None
        for entry in cands:
            if not use_index:
                # linear (kill-switch) path: rule + selector checks per
                # entry, today's scan shape — candidates from the index
                # already passed both at selection time.
                if not self._rules_match(entry, resource, op):
                    continue
                if entry.ns_sel is not None and ns:
                    if ns_labels is None:
                        ns_labels = self._namespace_labels(ns)
                    if not match_label_selector(entry.ns_sel, ns_labels):
                        continue
            if env is None:
                env = make_env({"object": obj,
                                "oldObject": old_object,
                                "request": request,
                                "params": None,
                                "variables": _NO_VARS})
            self._eval_entry(entry, env)

    def _eval_entry(self, entry, env: dict) -> None:
        """Shared evaluation core (both dispatch paths): matchConditions
        prefilter → per-binding params → auditAnnotations →
        validations. Raises PolicyDenied per failurePolicy. The
        evaluation counter batches into ONE inc per (entry, request) —
        counter locks were measurable at 30 evaluations/request on the
        1k-tenant shape — flushed on every exit path (deny included)
        by the finally."""
        nev = [0]
        try:
            self._eval_entry_inner(entry, env, nev)
        finally:
            if nev[0]:
                self.evaluations.inc_key(entry.ckey, nev[0])

    def _eval_entry_inner(self, entry, env: dict, nev: list) -> None:
        pname, fail_closed = entry.pname, entry.fail_closed
        if entry.conditions:
            # Prefilter stage: params is null during match evaluation
            # (conditions run before binding selection, like the
            # reference's stateless match when no paramRef applies).
            # Variables get their own memo for this stage — a value
            # computed under params=None must not leak into a
            # binding's validations.
            env["params"] = None
            env["variables"] = _LazyVars(entry.variables, env) \
                if entry.variables else _NO_VARS
            for cname, compiled in entry.conditions:
                nev[0] += 1
                try:
                    if isinstance(compiled, ExpressionError):
                        raise compiled
                    ok = compiled.evaluate_env(env)
                except ExpressionError as e:
                    if fail_closed:
                        self.rejections.inc(policy=pname)
                        raise PolicyDenied(
                            f'ValidatingAdmissionPolicy "{pname}" '
                            f"matchCondition {cname!r} failed and "
                            f"failurePolicy=Fail: {e}") from e
                    logger.warning("policy %s matchCondition %s: %s "
                                   "(Ignore)", pname, cname, e)
                    return
                if not ok:
                    return  # condition false: the policy does not apply
        annotated = False
        for binding, resolver in entry.bindings:
            try:
                params = resolver()
            except ExpressionError as e:
                if fail_closed:
                    self.rejections.inc(policy=pname)
                    raise PolicyDenied(
                        f'ValidatingAdmissionPolicy "{pname}" '
                        f"failed and failurePolicy=Fail: {e}") from e
                logger.warning("policy %s: %s (Ignore)", pname, e)
                continue
            env["params"] = params
            # Fresh variables memo per binding: each binding's params
            # differ, so a params-referencing variable must re-evaluate
            # under this binding's params rather than reuse the first
            # binding's (or the matchCondition stage's params=None)
            # value.
            env["variables"] = _LazyVars(entry.variables, env) \
                if entry.variables else _NO_VARS
            if entry.annotations and not annotated:
                annotated = True
                self._emit_annotations(entry, env, nev)
            for compiled, message, msg_expr in entry.validations:
                nev[0] += 1
                if isinstance(compiled, ExpressionError):
                    err: Exception | None = compiled
                    ok = None
                else:
                    try:
                        ok = compiled.evaluate_env(env)
                        err = None
                    except ExpressionError as e:
                        ok, err = None, e
                if err is not None:
                    if fail_closed:
                        self.rejections.inc(policy=pname)
                        raise PolicyDenied(
                            f'ValidatingAdmissionPolicy "{pname}" '
                            f"failed and failurePolicy=Fail: {err}")
                    logger.warning("policy %s: %s (Ignore)",
                                   pname, err)
                    continue
                if not ok:
                    self.rejections.inc(policy=pname)
                    msg = message
                    if msg_expr is not None:
                        # messageExpression failure falls back to the
                        # static message (reference), never failurePolicy.
                        try:
                            if not isinstance(msg_expr, ExpressionError):
                                m = msg_expr.evaluate_env(env)
                                if isinstance(m, str) and m:
                                    msg = m
                        except ExpressionError as e:
                            logger.warning(
                                "policy %s messageExpression: %s",
                                pname, e)
                    src = getattr(compiled, "source", "")
                    raise PolicyDenied(
                        f'ValidatingAdmissionPolicy "{pname}" '
                        f"denied the request: "
                        f"{msg or 'failed expression: ' + src}")

    def _emit_annotations(self, entry, env: dict, nev: list) -> None:
        """auditAnnotations: value expressions evaluated once per
        (policy, request) — a string publishes
        `annotations["<policy>/<key>"]` on the request's audit event
        (the contextvar seam in policy/audit.py), null omits, anything
        else is an error subject to failurePolicy."""
        from kubernetes_tpu.policy.audit import annotate
        for key, compiled in entry.annotations:
            nev[0] += 1
            try:
                if isinstance(compiled, ExpressionError):
                    raise compiled
                value = compiled.evaluate_env(env)
                if value is not None and not isinstance(value, str):
                    raise ExpressionError(
                        f"auditAnnotation {key!r} must evaluate to a "
                        f"string or null, got {type(value).__name__}")
            except ExpressionError as e:
                if entry.fail_closed:
                    self.rejections.inc(policy=entry.pname)
                    raise PolicyDenied(
                        f'ValidatingAdmissionPolicy "{entry.pname}" '
                        f"failed and failurePolicy=Fail: {e}") from e
                logger.warning("policy %s auditAnnotation %s: %s "
                               "(Ignore)", entry.pname, key, e)
                continue
            if value is not None:
                annotate(f"{entry.pname}/{key}", value)
