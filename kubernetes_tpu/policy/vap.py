"""ValidatingAdmissionPolicy evaluation over stored resources.

Parity target: `staging/src/k8s.io/apiserver/pkg/admission/plugin/
policy/validating` — ValidatingAdmissionPolicy + Binding objects
(admissionregistration.k8s.io/v1) stored via the API, evaluated in the
admission chain BEFORE validating webhooks. Shape subset:

    kind: ValidatingAdmissionPolicy
    spec:
      failurePolicy: Fail | Ignore          # default Fail, the reference
      paramKind: {kind: ConfigMap}          # optional params resource
      matchConstraints:
        resourceRules:
        - resources: ["pods"]               # "*" allowed
          operations: ["CREATE", "UPDATE"]  # default "*"
        namespaceSelector: {matchLabels: ...}   # labels of the OBJECT'S
                                                # Namespace (api/labels)
      validations:
      - expression: "object.spec.replicas <= params.data.maxReplicas"
        message: "replica cap"
        reason: Invalid

    kind: ValidatingAdmissionPolicyBinding
    spec:
      policyName: replica-cap
      paramRef: {name: cap, namespace: default}   # optional

A policy only runs where a binding selects it (the reference contract);
params resolve via the binding's paramRef against the policy's
paramKind. Expression failures (compile error, missing param, budget
exhaustion) obey failurePolicy: Fail denies, Ignore skips — exactly the
webhook-unreachable semantics next door in apiserver/admission.py.

Metrics: `policy_evaluations_total{policy=}` and
`policy_rejections_total{policy=}` (satellite: the bench detail JSON
reports the measured-phase deltas so a policy-chain regression is data).
"""

from __future__ import annotations

import logging
from typing import Any, Mapping

from kubernetes_tpu.api.labels import match_label_selector
from kubernetes_tpu.api.meta import name_of, namespace_of
from kubernetes_tpu.metrics.registry import Registry
from kubernetes_tpu.policy.expr import (
    CompiledExpression,
    ExpressionError,
    compile_expression,
    make_env,
)
from kubernetes_tpu.store.mvcc import Invalid

logger = logging.getLogger(__name__)

POLICY_RESOURCE = "validatingadmissionpolicies"
BINDING_RESOURCE = "validatingadmissionpolicybindings"


class PolicyDenied(Invalid):
    """A validation expression evaluated false (or failed with
    failurePolicy=Fail). Maps to 422/Invalid on both wires, carrying the
    policy's message in the returned Status."""


class PolicyEngine:
    """Evaluates the stored VAP set for one (object, resource, op).

    Reads policies/bindings live from the store tables each admit (the
    reference watches them via informers; in-process tables are the
    same freshness for free) and caches compiled expressions per
    (policy name, resourceVersion)."""

    def __init__(self, store, registry: Registry | None = None):
        self.store = store
        r = registry or Registry()
        self.registry = r
        self.evaluations = r.counter(
            "policy_evaluations_total",
            "ValidatingAdmissionPolicy expressions evaluated",
            labels=("policy",))
        self.rejections = r.counter(
            "policy_rejections_total",
            "Requests denied by a ValidatingAdmissionPolicy",
            labels=("policy",))
        #: policy name -> (resourceVersion, [CompiledExpression | error])
        self._compiled: dict[str, tuple[str, list]] = {}
        #: prebuilt [(policy, fail_closed, bindings, validations)] for
        #: the admission hot path, invalidated by store mutators on the
        #: two policy tables (O(1) per write, zero rescans per admit).
        self._active: list | None = None

        def invalidate(_obj, _self=self):
            _self._active = None

        for table in (POLICY_RESOURCE, BINDING_RESOURCE):
            store.register_mutator(
                table, invalidate, on=("create", "update", "delete"))

    def register_into(self, registry: Registry) -> None:
        """Surface the counters through another registry's render (the
        WatchMetrics pattern — same Counter objects, one truth)."""
        for c in (self.evaluations, self.rejections):
            registry._metrics.setdefault(c.name, c)

    # -- store access ------------------------------------------------------

    def _bindings_for(self, policy_name: str) -> list[dict]:
        return [b for b in self.store._table(BINDING_RESOURCE).values()
                if (b.get("spec") or {}).get("policyName") == policy_name]

    def _compiled_validations(self, policy: Mapping) -> list:
        """Compile-once per (name, rv); entries are CompiledExpression or
        the ExpressionError the compile raised (so a broken expression
        keeps obeying failurePolicy instead of recompiling per request)."""
        name = name_of(policy)
        rv = policy.get("metadata", {}).get("resourceVersion", "")
        cached = self._compiled.get(name)
        if cached is not None and cached[0] == rv:
            return cached[1]
        out = []
        for v in (policy.get("spec") or {}).get("validations") or []:
            try:
                out.append((compile_expression(v.get("expression", "")),
                            v.get("message", "")))
            except ExpressionError as e:
                out.append((e, v.get("message", "")))
        self._compiled[name] = (rv, out)
        return out

    def _namespace_labels(self, namespace: str) -> Mapping[str, str]:
        ns_obj = self.store._table("namespaces").get(namespace)
        if ns_obj is None:
            return {}
        return ns_obj.get("metadata", {}).get("labels") or {}

    def _resolve_params(self, policy: Mapping,
                        binding: Mapping) -> Any:
        """paramRef → the stored param object (or None when the policy
        takes no params). Raises ExpressionError when a configured param
        is missing — subject to failurePolicy, like the reference's
        paramNotFoundAction default."""
        param_kind = ((policy.get("spec") or {}).get("paramKind")
                      or {}).get("kind")
        ref = (binding.get("spec") or {}).get("paramRef") or {}
        if not param_kind or not ref.get("name"):
            return None
        resource = self.store.resource_for_kind(param_kind)
        if resource is None:
            raise ExpressionError(
                f"paramKind {param_kind!r} has no known resource")
        if self.store.is_cluster_scoped(resource):
            key = ref["name"]
        else:
            # A namespaced paramKind always needs a namespaced key — an
            # omitted paramRef.namespace defaults rather than building a
            # bare key that can never match (which, under
            # failurePolicy=Fail, would deny every request).
            key = f"{ref.get('namespace') or 'default'}/{ref['name']}"
        params = self.store._table(resource).get(key)
        if params is None:
            raise ExpressionError(
                f"param {param_kind} {key!r} not found")
        return params

    # -- evaluation --------------------------------------------------------

    def _active_set(self) -> list:
        """One prebuilt entry per bound policy — rebuilt only after a
        policy/binding table write (the mutators above clear it); the
        admission hot path just iterates. resourceRules precompile to
        frozenset pairs, counter label tuples precompute."""
        active = self._active
        if active is None:
            active = []
            for policy in self.store._table(POLICY_RESOURCE).values():
                pname = name_of(policy)
                bindings = self._bindings_for(pname)
                if not bindings:
                    continue  # unbound policies are inert (reference)
                spec = policy.get("spec") or {}
                constraints = spec.get("matchConstraints") or {}
                rule_sets = None  # None = match everything (reference)
                if constraints.get("resourceRules"):
                    rule_sets = [
                        (frozenset(rule.get("resources") or ()),
                         frozenset(str(o).upper() for o in
                                   rule.get("operations") or ["*"]))
                        for rule in constraints["resourceRules"]]
                active.append((
                    policy, pname,
                    spec.get("failurePolicy", "Fail") != "Ignore",
                    bindings, self._compiled_validations(policy),
                    rule_sets, constraints.get("namespaceSelector"),
                    (pname,)))
            self._active = active
        return active

    def validate(self, obj: Mapping, resource: str, operation: str, *,
                 old_object: Mapping | None = None,
                 user: str | None = None,
                 groups: list[str] | None = None) -> None:
        """Run every bound, matching policy; raise PolicyDenied on the
        first failing validation (Fail semantics) — Ignore-policy errors
        are logged and skipped."""
        active = self._active_set()
        if not active:
            return
        ns = namespace_of(obj)
        ns_labels: Mapping[str, str] | None = None
        op = operation.upper()
        request = {
            "operation": op,
            "resource": resource,
            "namespace": ns,
            "name": name_of(obj),
            "userInfo": {"username": user or "",
                         "groups": list(groups or [])},
        }
        #: one env shared by every expression this admit evaluates —
        #: only `params` varies per binding (expr.make_env contract).
        env: dict | None = None
        for (policy, pname, fail_closed, bindings, validations,
             rule_sets, ns_sel, ckey) in active:
            if rule_sets is not None and not any(
                    ("*" in rs or resource in rs)
                    and ("*" in ops or op in ops)
                    for rs, ops in rule_sets):
                continue
            if ns_sel is not None and ns:
                if ns_labels is None:
                    ns_labels = self._namespace_labels(ns)
                if not match_label_selector(ns_sel, ns_labels):
                    continue
            for binding in bindings:
                try:
                    params = self._resolve_params(policy, binding)
                except ExpressionError as e:
                    if fail_closed:
                        self.rejections.inc(policy=pname)
                        raise PolicyDenied(
                            f'ValidatingAdmissionPolicy "{pname}" '
                            f"failed and failurePolicy=Fail: {e}") from e
                    logger.warning("policy %s: %s (Ignore)", pname, e)
                    continue
                if env is None:
                    env = make_env({"object": obj,
                                    "oldObject": old_object,
                                    "request": request})
                env["params"] = params
                for compiled, message in validations:
                    self.evaluations.inc_key(ckey)
                    if isinstance(compiled, ExpressionError):
                        err: Exception = compiled
                        ok = None
                    else:
                        try:
                            ok = compiled.evaluate_env(env)
                            err = None
                        except ExpressionError as e:
                            ok, err = None, e
                    if err is not None:
                        if fail_closed:
                            self.rejections.inc(policy=pname)
                            raise PolicyDenied(
                                f'ValidatingAdmissionPolicy "{pname}" '
                                f"failed and failurePolicy=Fail: {err}")
                        logger.warning("policy %s: %s (Ignore)",
                                       pname, err)
                        continue
                    if not ok:
                        self.rejections.inc(policy=pname)
                        src = getattr(compiled, "source", "")
                        raise PolicyDenied(
                            f'ValidatingAdmissionPolicy "{pname}" '
                            f"denied the request: "
                            f"{message or 'failed expression: ' + src}")
