"""Policy-driven audit pipeline (SURVEY §5.5: levels, RequestReceived →
ResponseComplete stages).

Parity target: `staging/src/k8s.io/apiserver/pkg/audit` + the
audit.k8s.io Policy file shape:

    apiVersion: audit.k8s.io/v1
    kind: Policy
    rules:
    - level: None
      users: ["system:kube-proxy"]
    - level: RequestResponse
      verbs: ["create", "update"]
      resources: ["pods"]
    - level: Metadata

First matching rule wins (the reference's policy checker); no match =
level None. Levels gate how much of the request rides the event:
Metadata = who/what/when + response code; Request adds the request
object; RequestResponse adds the response object too.

Each audited request emits two stage events sharing one auditID —
RequestReceived before the rest of the chain runs (so it carries the
pre-impersonation identity) and ResponseComplete after, carrying the
response status plus `impersonatedUser` when the impersonation filter
swapped identities mid-chain.

Sinks are bounded and async (the reference's buffered backend): `emit`
never blocks the serving path; overflow drops (counted,
`audit_events_dropped_total`) rather than backpressuring — the same
DropIfChannelFull stance as client/events.py. Production backends
(SURVEY §5.5):

- `RotatingFileSink` — the `--audit-log-path` analog with
  `--audit-log-maxsize` / `--audit-log-maxage` / `--audit-log-maxbackups`
  rotation (size OR age triggers; `audit.log.1` is the newest backup).
- `WebhookSink` — the `--audit-webhook-config` analog: batches events
  into one `EventList` POST, bounded queue, exponential-backoff retry;
  exhausted retries drop (counted), never backpressure.

Both ride the same emit/close seam `AuditPipeline` already uses, and
both guard their I/O with `locking.check_dispatch_seam` — the runtime
twin of ktpu-lint's LK206 (no lock held across file I/O or wire sends).
"""

from __future__ import annotations

import asyncio
import contextvars
import itertools
import json
import logging
import os
import time
from typing import Any, Mapping

from kubernetes_tpu.metrics.registry import Registry
from kubernetes_tpu.utils import tracing
from kubernetes_tpu.utils.locking import check_dispatch_seam

logger = logging.getLogger(__name__)

#: the request's open audit context (set by AuditPipeline.begin, cleared
#: at response_complete): the seam through which the admission chain —
#: notably VAP auditAnnotations (policy/vap.py) — attaches annotations
#: to the event without threading the context through every handler.
#: contextvars give per-task isolation, so concurrent requests on one
#: loop cannot cross-annotate.
_CURRENT_CTX: contextvars.ContextVar[dict | None] = \
    contextvars.ContextVar("ktpu_audit_ctx", default=None)


def annotate(key: str, value: str) -> None:
    """Attach `annotations[key] = value` to the current request's audit
    event (no-op when the request isn't audited). First writer wins per
    key, mirroring the reference's audit.AddAuditAnnotation."""
    ctx = _CURRENT_CTX.get()
    if ctx is not None:
        ctx.setdefault("annotations", {}).setdefault(key, value)

LEVEL_NONE = "None"
LEVEL_METADATA = "Metadata"
LEVEL_REQUEST = "Request"
LEVEL_REQUEST_RESPONSE = "RequestResponse"

_LEVEL_ORDER = {LEVEL_NONE: 0, LEVEL_METADATA: 1, LEVEL_REQUEST: 2,
                LEVEL_REQUEST_RESPONSE: 3}

STAGE_REQUEST_RECEIVED = "RequestReceived"
STAGE_RESPONSE_COMPLETE = "ResponseComplete"

_audit_seq = itertools.count(1)


def level_at_least(level: str, want: str) -> bool:
    return _LEVEL_ORDER.get(level, 0) >= _LEVEL_ORDER.get(want, 0)


class AuditPolicy:
    """Ordered rules; first match wins. Rule fields (all optional, all
    must match when present): users, groups, verbs, resources,
    namespaces. `omitStages` drops stages per rule."""

    _LIST_FIELDS = ("users", "groups", "verbs", "resources",
                    "namespaces", "omitStages")

    def __init__(self, rules: list[Mapping] | None = None):
        self.rules = [dict(r) for r in rules or []]
        for rule in self.rules:
            for f in self._LIST_FIELDS:
                v = rule.get(f)
                if isinstance(v, str):
                    # A YAML scalar where a list belongs would silently
                    # degrade `value in want` to SUBSTRING matching.
                    rule[f] = [v]

    @classmethod
    def from_dict(cls, doc: Mapping | None) -> "AuditPolicy":
        return cls((doc or {}).get("rules") or [])

    @classmethod
    def from_file(cls, path: str) -> "AuditPolicy":
        import yaml
        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f))

    @classmethod
    def metadata_for_all(cls) -> "AuditPolicy":
        return cls([{"level": LEVEL_METADATA}])

    @staticmethod
    def _match(rule: Mapping, value: str | None, field: str) -> bool:
        want = rule.get(field)
        if not want:
            return True
        return (value or "") in want or "*" in want

    def rule_for(self, *, user: str | None = None,
                 groups: list[str] | None = None,
                 verb: str | None = None, resource: str | None = None,
                 namespace: str | None = None) -> Mapping | None:
        for rule in self.rules:
            if not self._match(rule, user, "users"):
                continue
            if rule.get("groups") and not any(
                    g in rule["groups"] for g in groups or []):
                continue
            if not self._match(rule, verb, "verbs"):
                continue
            if not self._match(rule, resource, "resources"):
                continue
            if not self._match(rule, namespace, "namespaces"):
                continue
            return rule
        return None

    def level_for(self, **attrs) -> str:
        rule = self.rule_for(**attrs)
        return rule.get("level", LEVEL_NONE) if rule else LEVEL_NONE


class AuditSink:
    """Bounded async JSON-lines writer. With `path=None` events collect
    in-memory (`self.entries`) — the test/bench sink; with a path they
    append as one JSON object per line, batched per drain pass."""

    MAX_PENDING = 4096
    #: in-memory retention cap (path=None): the serving path must not
    #: grow memory without bound under long runs.
    MAX_ENTRIES = 100_000

    def __init__(self, path: str | None = None,
                 registry: Registry | None = None):
        self.path = path
        self.entries: list[dict] = []
        r = registry or Registry()
        self.registry = r
        self.events_total = r.counter(
            "audit_events_total", "Audit stage events emitted",
            labels=("stage",))
        self.events_dropped = r.counter(
            "audit_events_dropped_total",
            "Audit events dropped on sink overflow")
        self._pending: list[dict] = []
        self._draining = False
        self._closed = False

    def register_into(self, registry: Registry) -> None:
        for c in (self.events_total, self.events_dropped):
            registry._metrics.setdefault(c.name, c)

    def emit(self, entry: dict) -> None:
        """Fire-and-forget enqueue; never blocks the handler chain."""
        if self._closed:
            return
        if len(self._pending) >= self.MAX_PENDING:
            self.events_dropped.inc()
            return
        self.events_total.inc(stage=entry.get("stage", ""))
        self._pending.append(entry)
        self._kick()

    def _kick(self) -> None:
        if self._draining or not self._pending:
            return
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            # No loop (sync contexts): drain inline to the memory sink so
            # nothing silently sits forever; file sinks flush on close.
            if self.path is None:
                self._absorb(self._pending)
                self._pending = []
            return
        self._draining = True
        asyncio.ensure_future(self._drain())

    def _absorb(self, batch: list[dict]) -> None:
        self.entries.extend(batch)
        if len(self.entries) > self.MAX_ENTRIES:
            del self.entries[:len(self.entries) - self.MAX_ENTRIES]

    def _write_batch(self, batch: list[dict]) -> None:
        """One buffered append per batch; the event loop eats a short
        write rather than a thread handoff per line. The rotation
        subclass hooks _before_append/_after_append — serialization and
        the dispatch-seam guard (the LK206 runtime twin) live HERE
        only. Never called with a lock held."""
        check_dispatch_seam("audit.file_write")
        lines = "".join(
            json.dumps(e, separators=(",", ":")) + "\n" for e in batch)
        self._before_append(len(lines))
        with open(self.path, "a") as f:
            f.write(lines)
        self._after_append(len(lines))

    def _before_append(self, nbytes: int) -> None:
        """Hook: called with the serialized batch size before the
        append (RotatingFileSink rotates here)."""

    def _after_append(self, nbytes: int) -> None:
        """Hook: called after a successful append."""

    async def _drain(self) -> None:
        try:
            while self._pending:
                batch, self._pending = self._pending, []
                if self.path is None:
                    self._absorb(batch)
                    continue
                try:
                    self._write_batch(batch)
                except OSError:
                    logger.exception("audit sink write failed "
                                     "(%d events lost)", len(batch))
                    self.events_dropped.inc(len(batch))
                await asyncio.sleep(0)  # yield between batches
        finally:
            self._draining = False

    async def close(self) -> None:
        """Flush whatever is still buffered, then refuse new events."""
        for _ in range(100):
            if not self._pending and not self._draining:
                break
            self._kick()
            await asyncio.sleep(0.01)
        self._closed = True
        if self._pending:
            # Drain task never caught up (slow disk, dying loop): flush
            # the tail inline — and if even that fails, the loss is
            # COUNTED, never silent (the module's drop contract).
            batch, self._pending = self._pending, []
            if self.path is None:
                self._absorb(batch)
            else:
                try:
                    self._write_batch(batch)
                except OSError:
                    logger.exception("audit sink close lost %d events",
                                     len(batch))
                    self.events_dropped.inc(len(batch))


class RotatingFileSink(AuditSink):
    """Size/age-rotated JSON-lines file sink — the reference's
    `--audit-log-path` + `--audit-log-maxsize`/`--audit-log-maxage`/
    `--audit-log-maxbackups` backend.

    Rotation happens at batch-write time (before the append that would
    cross the size bound, or once the open segment outlives max_age_s):
    `path` renames to `path.1`, existing backups shift up, anything past
    `backups` is deleted. Writes stay on the event loop like the base
    sink — one short buffered append per batch, no locks held (the
    dispatch-seam guard in `_write_batch` enforces it under
    KTPU_LOCK_CHECK)."""

    def __init__(self, path: str, *, max_bytes: int = 10 * 2 ** 20,
                 max_age_s: float | None = None, backups: int = 5,
                 registry: Registry | None = None):
        super().__init__(path=path, registry=registry)
        self.max_bytes = max(1, int(max_bytes))
        self.max_age_s = max_age_s
        self.backups = max(0, int(backups))
        self.rotations = self.registry.counter(
            "audit_log_rotations_total",
            "Audit log file rotations (size or age trigger)")
        try:
            self._size = os.path.getsize(path)
        except OSError:
            self._size = 0
        self._opened_at = time.monotonic()

    def register_into(self, registry: Registry) -> None:
        super().register_into(registry)
        registry._metrics.setdefault(self.rotations.name, self.rotations)

    def _should_rotate(self, incoming: int) -> bool:
        if self._size and self._size + incoming > self.max_bytes:
            return True
        return (self.max_age_s is not None and self._size
                and time.monotonic() - self._opened_at >= self.max_age_s)

    def _rotate(self) -> None:
        if self.backups == 0:
            try:
                os.remove(self.path)
            except OSError:
                pass
        else:
            for i in range(self.backups - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            try:
                os.replace(self.path, f"{self.path}.1")
            except OSError:
                pass
        self._size = 0
        self._opened_at = time.monotonic()
        self.rotations.inc()

    def _before_append(self, nbytes: int) -> None:
        if self._should_rotate(nbytes):
            self._rotate()

    def _after_append(self, nbytes: int) -> None:
        self._size += nbytes


class WebhookSink:
    """Batching audit webhook — the reference's `--audit-webhook-config`
    backend: events buffer into a bounded queue and a loop-resident
    worker POSTs them as one `audit.k8s.io/v1 EventList` per batch, with
    exponential-backoff retry. A batch that exhausts its retries drops
    (counted) — the pipeline never backpressures the serving path, and
    never blocks a second batch behind a dead endpoint forever.

    Duck-compatible with AuditSink where AuditPipeline cares (emit /
    close / register_into / events_total / events_dropped). `post` is
    the transport seam — default aiohttp POST of the config's `url`;
    tests inject a local server or a callable."""

    MAX_PENDING = 4096

    def __init__(self, url: str, *, batch_max: int = 400,
                 initial_backoff: float = 0.25, max_retries: int = 4,
                 timeout: float = 10.0,
                 registry: Registry | None = None, post=None):
        self.url = url
        self.batch_max = max(1, int(batch_max))
        self.initial_backoff = initial_backoff
        self.max_retries = max(0, int(max_retries))
        self.timeout = timeout
        r = registry or Registry()
        self.registry = r
        self.events_total = r.counter(
            "audit_events_total", "Audit stage events emitted",
            labels=("stage",))
        self.events_dropped = r.counter(
            "audit_events_dropped_total",
            "Audit events dropped on sink overflow")
        self.webhook_batches = r.counter(
            "audit_webhook_batches_total",
            "Audit webhook batch deliveries attempted",
            labels=("outcome",))
        self.webhook_retries = r.counter(
            "audit_webhook_retries_total",
            "Audit webhook batch retry attempts after a failed POST")
        self._post = post
        self._session = None
        self._pending: list[dict] = []
        self._draining = False
        self._drain_task: asyncio.Task | None = None
        self._closed = False

    @classmethod
    def from_config(cls, path: str,
                    registry: Registry | None = None) -> "WebhookSink":
        """Build from a YAML config file:

            url: http://collector:9099/audit
            batch: {maxSize: 400}
            retry: {backoff: 0.25, maxAttempts: 4}
        """
        import yaml
        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        url = doc.get("url")
        if not url:
            raise ValueError(f"audit webhook config {path}: url required")
        batch = doc.get("batch") or {}
        retry = doc.get("retry") or {}
        return cls(url, batch_max=batch.get("maxSize", 400),
                   initial_backoff=retry.get("backoff", 0.25),
                   max_retries=retry.get("maxAttempts", 4),
                   registry=registry)

    def register_into(self, registry: Registry) -> None:
        for c in (self.events_total, self.events_dropped,
                  self.webhook_batches, self.webhook_retries):
            registry._metrics.setdefault(c.name, c)

    def emit(self, entry: dict) -> None:
        """Fire-and-forget enqueue; never blocks the handler chain."""
        if self._closed:
            return
        if len(self._pending) >= self.MAX_PENDING:
            self.events_dropped.inc()
            return
        self.events_total.inc(stage=entry.get("stage", ""))
        self._pending.append(entry)
        self._kick()

    def _kick(self) -> None:
        if self._draining or not self._pending:
            return
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop: events wait for close()'s final flush
        self._draining = True
        self._drain_task = asyncio.ensure_future(self._drain())

    async def _send(self, batch: list[dict]) -> None:
        """One EventList POST. The dispatch-seam guard is the runtime
        twin of LK206 — the worker must not hold a lock across the
        wire send."""
        check_dispatch_seam("audit.webhook_send")
        body = {"kind": "EventList", "apiVersion": "audit.k8s.io/v1",
                "items": batch}
        if self._post is not None:
            await self._post(self.url, body)
            return
        import aiohttp
        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout))
        async with self._session.post(self.url, json=body) as resp:
            resp.raise_for_status()

    async def _deliver(self, batch: list[dict]) -> None:
        backoff = self.initial_backoff
        for attempt in range(self.max_retries + 1):
            try:
                await self._send(batch)
                self.webhook_batches.inc(outcome="ok")
                return
            except asyncio.CancelledError:
                raise
            except Exception as e:
                if attempt == self.max_retries:
                    self.webhook_batches.inc(outcome="failed")
                    self.events_dropped.inc(len(batch))
                    logger.warning(
                        "audit webhook %s: batch of %d dropped after "
                        "%d attempts: %s", self.url, len(batch),
                        attempt + 1, e)
                    return
                self.webhook_retries.inc()
                await asyncio.sleep(backoff)
                backoff *= 2

    async def _drain(self) -> None:
        try:
            while self._pending:
                batch = self._pending[:self.batch_max]
                del self._pending[:self.batch_max]
                await self._deliver(batch)
        finally:
            self._draining = False

    async def close(self) -> None:
        """Flush the queue (retries included), then refuse new events
        and close the transport. AWAITS the in-flight drain task rather
        than racing it: stealing its batches while it sleeps in a retry
        backoff would let it wake after the session is closed and mint
        a fresh one nothing ever closes."""
        self._closed = True
        task = self._drain_task
        if task is not None and not task.done():
            try:
                await task
            except asyncio.CancelledError:
                raise
            except Exception:  # _deliver already counted the loss
                logger.exception("audit webhook drain failed in close")
        while self._pending:
            batch = self._pending[:self.batch_max]
            del self._pending[:self.batch_max]
            await self._deliver(batch)
        if self._session is not None:
            await self._session.close()
            self._session = None


class AuditPipeline:
    """Policy + sink + stage-event construction, shared by the HTTP
    middleware, the KTPU wire handler, and the gRPC interceptor."""

    def __init__(self, policy: AuditPolicy | None = None,
                 sink: AuditSink | None = None,
                 registry: Registry | None = None):
        self.policy = policy or AuditPolicy()
        self.sink = sink or AuditSink(registry=registry)

    def register_into(self, registry: Registry) -> None:
        self.sink.register_into(registry)

    # -- stage events ------------------------------------------------------

    _RULE_UNSET = object()

    def begin(self, *, user: str, groups: list[str] | None = None,
              verb: str, resource: str, namespace: str | None = None,
              name: str | None = None, request_object: Any = None,
              rule: Any = _RULE_UNSET) -> dict | None:
        """Emit RequestReceived; returns the audit context to finish with
        response_complete(), or None when the policy says level None
        (nothing more to do for this request). Callers that already
        matched the policy (to decide whether to capture the body) pass
        the rule in — the scan must not run twice per request."""
        if rule is self._RULE_UNSET:
            rule = self.policy.rule_for(user=user, groups=groups,
                                        verb=verb, resource=resource,
                                        namespace=namespace)
        level = rule.get("level", LEVEL_NONE) if rule else LEVEL_NONE
        if level == LEVEL_NONE:
            # Clear the annotation seam: on a long-lived wire task a
            # stale context from the PREVIOUS op must not collect this
            # request's annotations.
            _CURRENT_CTX.set(None)
            return None
        omit = set((rule or {}).get("omitStages") or ())
        ctx = {
            "kind": "Event", "apiVersion": "audit.k8s.io/v1",
            "auditID": f"audit-{next(_audit_seq):x}",
            "level": level,
            "verb": verb,
            "user": {"username": user, "groups": list(groups or [])},
            "objectRef": {"resource": resource,
                          "namespace": namespace or "",
                          "name": name or ""},
        }
        # Trace ↔ audit correlation (§5.1 ↔ §5.5): when this request runs
        # inside a span, the audit event carries the span's traceparent
        # annotation and the span carries the auditID attribute — one
        # pod's create→admit→schedule→bind path joins on either key.
        sp = tracing.current_span()
        if sp is not None:
            sp.attrs.setdefault("audit_id", ctx["auditID"])
            ctx["annotations"] = {
                "traceparent": tracing.format_traceparent(
                    sp.trace_id, sp.span_id)}
        if level_at_least(level, LEVEL_REQUEST) and \
                request_object is not None:
            ctx["requestObject"] = request_object
        if STAGE_REQUEST_RECEIVED not in omit:
            self.sink.emit({**ctx, "stage": STAGE_REQUEST_RECEIVED,
                            "stageTimestamp": _now()})
        ctx["_omit"] = omit
        # Open the annotation seam: chain stages running under this
        # request (VAP auditAnnotations, webhooks) attach to this event
        # via annotate() — annotations land on ResponseComplete, the
        # stage emitted after they are set.
        _CURRENT_CTX.set(ctx)
        return ctx

    def response_complete(self, ctx: dict | None, *, code: int,
                          response_object: Any = None,
                          impersonated_user: str | None = None,
                          request_object: Any = None) -> None:
        """Emit ResponseComplete for a begin()-opened context. Records
        both identities when impersonation happened mid-chain: `user`
        stays the authenticated (original) principal, `impersonatedUser`
        is who the request ran as."""
        if ctx is None:
            return
        if _CURRENT_CTX.get() is ctx:
            _CURRENT_CTX.set(None)
        omit = ctx.pop("_omit", set())
        if STAGE_RESPONSE_COMPLETE in omit:
            return
        entry = {k: v for k, v in ctx.items() if not k.startswith("_")}
        entry["stage"] = STAGE_RESPONSE_COMPLETE
        entry["stageTimestamp"] = _now()
        entry["responseStatus"] = {"code": code}
        if impersonated_user:
            entry["impersonatedUser"] = {"username": impersonated_user}
        level = ctx.get("level", LEVEL_NONE)
        if level_at_least(level, LEVEL_REQUEST) and \
                request_object is not None and \
                "requestObject" not in entry:
            entry["requestObject"] = request_object
        if level_at_least(level, LEVEL_REQUEST_RESPONSE) and \
                response_object is not None:
            entry["responseObject"] = response_object
        self.sink.emit(entry)

    async def close(self) -> None:
        await self.sink.close()


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
