"""Policy-driven audit pipeline (SURVEY §5.5: levels, RequestReceived →
ResponseComplete stages).

Parity target: `staging/src/k8s.io/apiserver/pkg/audit` + the
audit.k8s.io Policy file shape:

    apiVersion: audit.k8s.io/v1
    kind: Policy
    rules:
    - level: None
      users: ["system:kube-proxy"]
    - level: RequestResponse
      verbs: ["create", "update"]
      resources: ["pods"]
    - level: Metadata

First matching rule wins (the reference's policy checker); no match =
level None. Levels gate how much of the request rides the event:
Metadata = who/what/when + response code; Request adds the request
object; RequestResponse adds the response object too.

Each audited request emits two stage events sharing one auditID —
RequestReceived before the rest of the chain runs (so it carries the
pre-impersonation identity) and ResponseComplete after, carrying the
response status plus `impersonatedUser` when the impersonation filter
swapped identities mid-chain.

The sink is a bounded async JSON-lines writer (the reference's buffered
backend): `emit` never blocks the serving path; overflow drops (counted,
`audit_events_dropped_total`) rather than backpressuring — the same
DropIfChannelFull stance as client/events.py.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import time
from typing import Any, Mapping

from kubernetes_tpu.metrics.registry import Registry
from kubernetes_tpu.utils import tracing

logger = logging.getLogger(__name__)

LEVEL_NONE = "None"
LEVEL_METADATA = "Metadata"
LEVEL_REQUEST = "Request"
LEVEL_REQUEST_RESPONSE = "RequestResponse"

_LEVEL_ORDER = {LEVEL_NONE: 0, LEVEL_METADATA: 1, LEVEL_REQUEST: 2,
                LEVEL_REQUEST_RESPONSE: 3}

STAGE_REQUEST_RECEIVED = "RequestReceived"
STAGE_RESPONSE_COMPLETE = "ResponseComplete"

_audit_seq = itertools.count(1)


def level_at_least(level: str, want: str) -> bool:
    return _LEVEL_ORDER.get(level, 0) >= _LEVEL_ORDER.get(want, 0)


class AuditPolicy:
    """Ordered rules; first match wins. Rule fields (all optional, all
    must match when present): users, groups, verbs, resources,
    namespaces. `omitStages` drops stages per rule."""

    _LIST_FIELDS = ("users", "groups", "verbs", "resources",
                    "namespaces", "omitStages")

    def __init__(self, rules: list[Mapping] | None = None):
        self.rules = [dict(r) for r in rules or []]
        for rule in self.rules:
            for f in self._LIST_FIELDS:
                v = rule.get(f)
                if isinstance(v, str):
                    # A YAML scalar where a list belongs would silently
                    # degrade `value in want` to SUBSTRING matching.
                    rule[f] = [v]

    @classmethod
    def from_dict(cls, doc: Mapping | None) -> "AuditPolicy":
        return cls((doc or {}).get("rules") or [])

    @classmethod
    def from_file(cls, path: str) -> "AuditPolicy":
        import yaml
        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f))

    @classmethod
    def metadata_for_all(cls) -> "AuditPolicy":
        return cls([{"level": LEVEL_METADATA}])

    @staticmethod
    def _match(rule: Mapping, value: str | None, field: str) -> bool:
        want = rule.get(field)
        if not want:
            return True
        return (value or "") in want or "*" in want

    def rule_for(self, *, user: str | None = None,
                 groups: list[str] | None = None,
                 verb: str | None = None, resource: str | None = None,
                 namespace: str | None = None) -> Mapping | None:
        for rule in self.rules:
            if not self._match(rule, user, "users"):
                continue
            if rule.get("groups") and not any(
                    g in rule["groups"] for g in groups or []):
                continue
            if not self._match(rule, verb, "verbs"):
                continue
            if not self._match(rule, resource, "resources"):
                continue
            if not self._match(rule, namespace, "namespaces"):
                continue
            return rule
        return None

    def level_for(self, **attrs) -> str:
        rule = self.rule_for(**attrs)
        return rule.get("level", LEVEL_NONE) if rule else LEVEL_NONE


class AuditSink:
    """Bounded async JSON-lines writer. With `path=None` events collect
    in-memory (`self.entries`) — the test/bench sink; with a path they
    append as one JSON object per line, batched per drain pass."""

    MAX_PENDING = 4096
    #: in-memory retention cap (path=None): the serving path must not
    #: grow memory without bound under long runs.
    MAX_ENTRIES = 100_000

    def __init__(self, path: str | None = None,
                 registry: Registry | None = None):
        self.path = path
        self.entries: list[dict] = []
        r = registry or Registry()
        self.registry = r
        self.events_total = r.counter(
            "audit_events_total", "Audit stage events emitted",
            labels=("stage",))
        self.events_dropped = r.counter(
            "audit_events_dropped_total",
            "Audit events dropped on sink overflow")
        self._pending: list[dict] = []
        self._draining = False
        self._closed = False

    def register_into(self, registry: Registry) -> None:
        for c in (self.events_total, self.events_dropped):
            registry._metrics.setdefault(c.name, c)

    def emit(self, entry: dict) -> None:
        """Fire-and-forget enqueue; never blocks the handler chain."""
        if self._closed:
            return
        if len(self._pending) >= self.MAX_PENDING:
            self.events_dropped.inc()
            return
        self.events_total.inc(stage=entry.get("stage", ""))
        self._pending.append(entry)
        self._kick()

    def _kick(self) -> None:
        if self._draining or not self._pending:
            return
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            # No loop (sync contexts): drain inline to the memory sink so
            # nothing silently sits forever; file sinks flush on close.
            if self.path is None:
                self._absorb(self._pending)
                self._pending = []
            return
        self._draining = True
        asyncio.ensure_future(self._drain())

    def _absorb(self, batch: list[dict]) -> None:
        self.entries.extend(batch)
        if len(self.entries) > self.MAX_ENTRIES:
            del self.entries[:len(self.entries) - self.MAX_ENTRIES]

    async def _drain(self) -> None:
        try:
            while self._pending:
                batch, self._pending = self._pending, []
                if self.path is None:
                    self._absorb(batch)
                    continue
                try:
                    lines = "".join(
                        json.dumps(e, separators=(",", ":")) + "\n"
                        for e in batch)
                    # One buffered append per batch; the event loop eats
                    # a short write rather than a thread handoff per line.
                    with open(self.path, "a") as f:
                        f.write(lines)
                except OSError:
                    logger.exception("audit sink write failed "
                                     "(%d events lost)", len(batch))
                    self.events_dropped.inc(len(batch))
                await asyncio.sleep(0)  # yield between batches
        finally:
            self._draining = False

    async def close(self) -> None:
        """Flush whatever is still buffered, then refuse new events."""
        for _ in range(100):
            if not self._pending and not self._draining:
                break
            self._kick()
            await asyncio.sleep(0.01)
        self._closed = True
        if self._pending:
            # Drain task never caught up (slow disk, dying loop): flush
            # the tail inline — and if even that fails, the loss is
            # COUNTED, never silent (the module's drop contract).
            batch, self._pending = self._pending, []
            if self.path is None:
                self._absorb(batch)
            else:
                try:
                    with open(self.path, "a") as f:
                        f.write("".join(
                            json.dumps(e, separators=(",", ":")) + "\n"
                            for e in batch))
                except OSError:
                    logger.exception("audit sink close lost %d events",
                                     len(batch))
                    self.events_dropped.inc(len(batch))


class AuditPipeline:
    """Policy + sink + stage-event construction, shared by the HTTP
    middleware, the KTPU wire handler, and the gRPC interceptor."""

    def __init__(self, policy: AuditPolicy | None = None,
                 sink: AuditSink | None = None,
                 registry: Registry | None = None):
        self.policy = policy or AuditPolicy()
        self.sink = sink or AuditSink(registry=registry)

    def register_into(self, registry: Registry) -> None:
        self.sink.register_into(registry)

    # -- stage events ------------------------------------------------------

    _RULE_UNSET = object()

    def begin(self, *, user: str, groups: list[str] | None = None,
              verb: str, resource: str, namespace: str | None = None,
              name: str | None = None, request_object: Any = None,
              rule: Any = _RULE_UNSET) -> dict | None:
        """Emit RequestReceived; returns the audit context to finish with
        response_complete(), or None when the policy says level None
        (nothing more to do for this request). Callers that already
        matched the policy (to decide whether to capture the body) pass
        the rule in — the scan must not run twice per request."""
        if rule is self._RULE_UNSET:
            rule = self.policy.rule_for(user=user, groups=groups,
                                        verb=verb, resource=resource,
                                        namespace=namespace)
        level = rule.get("level", LEVEL_NONE) if rule else LEVEL_NONE
        if level == LEVEL_NONE:
            return None
        omit = set((rule or {}).get("omitStages") or ())
        ctx = {
            "kind": "Event", "apiVersion": "audit.k8s.io/v1",
            "auditID": f"audit-{next(_audit_seq):x}",
            "level": level,
            "verb": verb,
            "user": {"username": user, "groups": list(groups or [])},
            "objectRef": {"resource": resource,
                          "namespace": namespace or "",
                          "name": name or ""},
        }
        # Trace ↔ audit correlation (§5.1 ↔ §5.5): when this request runs
        # inside a span, the audit event carries the span's traceparent
        # annotation and the span carries the auditID attribute — one
        # pod's create→admit→schedule→bind path joins on either key.
        sp = tracing.current_span()
        if sp is not None:
            sp.attrs.setdefault("audit_id", ctx["auditID"])
            ctx["annotations"] = {
                "traceparent": tracing.format_traceparent(
                    sp.trace_id, sp.span_id)}
        if level_at_least(level, LEVEL_REQUEST) and \
                request_object is not None:
            ctx["requestObject"] = request_object
        if STAGE_REQUEST_RECEIVED not in omit:
            self.sink.emit({**ctx, "stage": STAGE_REQUEST_RECEIVED,
                            "stageTimestamp": _now()})
        ctx["_omit"] = omit
        return ctx

    def response_complete(self, ctx: dict | None, *, code: int,
                          response_object: Any = None,
                          impersonated_user: str | None = None,
                          request_object: Any = None) -> None:
        """Emit ResponseComplete for a begin()-opened context. Records
        both identities when impersonation happened mid-chain: `user`
        stays the authenticated (original) principal, `impersonatedUser`
        is who the request ran as."""
        if ctx is None:
            return
        omit = ctx.pop("_omit", set())
        if STAGE_RESPONSE_COMPLETE in omit:
            return
        entry = {k: v for k, v in ctx.items() if not k.startswith("_")}
        entry["stage"] = STAGE_RESPONSE_COMPLETE
        entry["stageTimestamp"] = _now()
        entry["responseStatus"] = {"code": code}
        if impersonated_user:
            entry["impersonatedUser"] = {"username": impersonated_user}
        level = ctx.get("level", LEVEL_NONE)
        if level_at_least(level, LEVEL_REQUEST) and \
                request_object is not None and \
                "requestObject" not in entry:
            entry["requestObject"] = request_object
        if level_at_least(level, LEVEL_REQUEST_RESPONSE) and \
                response_object is not None:
            entry["responseObject"] = response_object
        self.sink.emit(entry)

    async def close(self) -> None:
        await self.sink.close()


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
