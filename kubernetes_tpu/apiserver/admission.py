"""Admission webhooks + CustomResourceDefinition support.

Parity targets:
- `pkg/admission/plugin/webhook/{mutating,validating}` + §3.2's handler
  chain: mutating webhooks run first (may patch the object), then
  validating webhooks (allow/deny) — both as HTTPS JSON out-calls carrying
  an AdmissionReview. Configurations are MutatingWebhookConfiguration /
  ValidatingWebhookConfiguration objects in the store; `failurePolicy:
  Ignore|Fail` governs unreachable webhooks. Patches use RFC-6902 JSON
  Patch (add/replace/remove), like the reference.
- `staging/src/k8s.io/apiextensions-apiserver`: CustomResourceDefinition
  objects register a new served resource — on this schemaless store that
  means wiring a structural-schema validator (openAPIV3Schema subset:
  type/properties/required/enum/items) and the kind→resource mapping so
  `ktpuctl apply` and the GC understand the new kind.
"""

from __future__ import annotations

import logging
from typing import Any, Mapping

from kubernetes_tpu.api.meta import name_of
from kubernetes_tpu.store.mvcc import Invalid, StoreError

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# RFC-6902 JSON Patch (add / replace / remove)
# ---------------------------------------------------------------------------

def _resolve(obj: Any, pointer: str) -> tuple[Any, str]:
    """Parent container + final token for a JSON pointer."""
    parts = [p.replace("~1", "/").replace("~0", "~")
             for p in pointer.lstrip("/").split("/")]
    cur = obj
    for p in parts[:-1]:
        cur = cur[int(p)] if isinstance(cur, list) else cur[p]
    return cur, parts[-1]


def apply_json_patch(obj: dict, patch: list[Mapping]) -> dict:
    for op in patch:
        kind = op.get("op")
        parent, tok = _resolve(obj, op.get("path", ""))
        if kind in ("add", "replace"):
            if isinstance(parent, list):
                idx = len(parent) if tok == "-" else int(tok)
                if kind == "add":
                    parent.insert(idx, op.get("value"))
                else:
                    parent[idx] = op.get("value")
            else:
                parent[tok] = op.get("value")
        elif kind == "remove":
            if isinstance(parent, list):
                parent.pop(int(tok))
            else:
                parent.pop(tok, None)
        else:
            raise Invalid(f"unsupported JSON patch op {kind!r}")
    return obj


# ---------------------------------------------------------------------------
# webhook dispatch
# ---------------------------------------------------------------------------

def _rules_match(webhook: Mapping, resource: str, operation: str) -> bool:
    op = operation.upper()  # rules carry CREATE/UPDATE/DELETE, wire-style
    for rule in webhook.get("rules") or []:
        resources = rule.get("resources") or []
        operations = [str(o).upper() for o in rule.get("operations") or ["*"]]
        if ("*" in resources or resource in resources) and \
                ("*" in operations or op in operations):
            return True
    return False


class WebhookAdmission:
    """Runs the admission chain for one (object, op, resource): mutating
    webhooks → ValidatingAdmissionPolicy expressions (policy/vap.py,
    when a PolicyEngine is attached) → validating webhooks — the
    reference plugin order (VAP sorts before ValidatingAdmissionWebhook
    in pkg/kubeapiserver/options/plugins.go)."""

    def __init__(self, store, timeout: float = 5.0, policy_engine=None):
        self.store = store
        self.timeout = timeout
        #: policy/vap.PolicyEngine or None = no expression policies.
        self.policy_engine = policy_engine
        self._session = None

    async def _post(self, url: str, review: dict) -> dict:
        import aiohttp
        if self._session is None:
            # Synchronous check+construct+assign (no await between them):
            # atomic under a single event loop, so concurrent admits can't
            # double-create the session.
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout))
        async with self._session.post(url, json=review) as resp:
            resp.raise_for_status()
            return await resp.json()

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    def _configs(self, table: str) -> list[dict]:
        return list(self.store._table(table).values())

    async def admit(self, obj: dict, resource: str, operation: str, *,
                    user: str | None = None,
                    groups: list[str] | None = None) -> dict:
        """Mutating chain (patches applied in order), then the
        ValidatingAdmissionPolicy stage, then the validating chain.
        Raises Invalid on deny; failurePolicy Fail treats an unreachable
        webhook as deny, Ignore (default here) skips it. `user`/`groups`
        feed the policy expressions' `request.userInfo`."""
        from kubernetes_tpu.utils.tracing import DEFAULT_TRACER
        if DEFAULT_TRACER.enabled:
            # Admission is the chain stage between the request span and
            # the store op — its own span so a slow webhook out-call or
            # policy evaluation is visible in the attempt tree.
            with DEFAULT_TRACER.span("admission.admit", resource=resource,
                                     op=operation):
                return await self._admit_chain(
                    obj, resource, operation, user=user, groups=groups)
        return await self._admit_chain(obj, resource, operation,
                                       user=user, groups=groups)

    async def _admit_chain(self, obj: dict, resource: str, operation: str,
                           *, user: str | None = None,
                           groups: list[str] | None = None) -> dict:
        for cfg in self._configs("mutatingwebhookconfigurations"):
            for wh in cfg.get("webhooks") or []:
                if not _rules_match(wh, resource, operation):
                    continue
                resp = await self._call(wh, obj, resource, operation)
                if resp is None:
                    continue
                if not resp.get("allowed", False):
                    raise Invalid(self._deny_msg(wh, resp))
                patch = resp.get("patch")
                if patch:
                    try:
                        obj = apply_json_patch(obj, patch)
                    except (Invalid, KeyError, ValueError, IndexError,
                            TypeError) as e:
                        # A bad patch is a webhook failure, subject to its
                        # failurePolicy (the reference behavior) — not a
                        # raw 500.
                        if wh.get("failurePolicy", "Ignore") == "Fail":
                            raise Invalid(
                                f'admission webhook '
                                f'"{wh.get("name", "?")}" returned an '
                                f"invalid patch: {e}") from e
                        logger.warning(
                            "ignoring invalid patch from webhook %s: %s",
                            wh.get("name"), e)
        if self.policy_engine is not None:
            if operation == "delete":
                # DELETE: the reference evaluates expressions with
                # `object=null` and the stored object as oldObject —
                # both wires hand the current object in as `obj` here.
                self.policy_engine.validate(
                    None, resource, operation, old_object=obj,
                    user=user, groups=groups)
            else:
                # Expression policies see the POST-mutation object; the
                # stored current object rides as oldObject on updates
                # (the reference passes the existing object from
                # storage).
                old = None
                if operation == "update":
                    from kubernetes_tpu.api.meta import namespaced_name
                    old = self.store._table(resource).get(
                        namespaced_name(obj))
                self.policy_engine.validate(
                    obj, resource, operation, old_object=old,
                    user=user, groups=groups)
        for cfg in self._configs("validatingwebhookconfigurations"):
            for wh in cfg.get("webhooks") or []:
                if not _rules_match(wh, resource, operation):
                    continue
                resp = await self._call(wh, obj, resource, operation)
                if resp is None:
                    continue
                if not resp.get("allowed", False):
                    raise Invalid(self._deny_msg(wh, resp))
        return obj

    @staticmethod
    def _deny_msg(wh: Mapping, resp: Mapping) -> str:
        msg = (resp.get("status") or {}).get("message", "denied")
        return f'admission webhook "{wh.get("name", "?")}" denied the ' \
               f"request: {msg}"

    async def _call(self, wh: Mapping, obj: dict, resource: str,
                    operation: str) -> dict | None:
        url = (wh.get("clientConfig") or {}).get("url")
        if not url:
            return None
        review = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "operation": operation.upper(),
                "resource": {"resource": resource},
                "object": obj,
            },
        }
        try:
            out = await self._post(url, review)
            return out.get("response") or {}
        except Exception as e:
            if wh.get("failurePolicy", "Ignore") == "Fail":
                raise Invalid(
                    f'admission webhook "{wh.get("name", "?")}" '
                    f"unreachable and failurePolicy=Fail: {e}") from e
            logger.warning("admission webhook %s unreachable (Ignore): %s",
                           wh.get("name"), e)
            return None


# ---------------------------------------------------------------------------
# CRDs: structural-schema-lite validation + kind registration
# ---------------------------------------------------------------------------

def validate_against_schema(value: Any, schema: Mapping, path: str = "") -> None:
    t = schema.get("type")
    if t == "object":
        if not isinstance(value, dict):
            raise Invalid(f"{path or '<root>'}: expected object")
        props = schema.get("properties") or {}
        for req in schema.get("required") or []:
            if req not in value:
                raise Invalid(f"{path}.{req}: required field missing")
        for k, v in value.items():
            sub = props.get(k)
            if sub is not None:
                validate_against_schema(v, sub, f"{path}.{k}")
    elif t == "array":
        if not isinstance(value, list):
            raise Invalid(f"{path}: expected array")
        items = schema.get("items")
        if items:
            for i, v in enumerate(value):
                validate_against_schema(v, items, f"{path}[{i}]")
    elif t == "string":
        if not isinstance(value, str):
            raise Invalid(f"{path}: expected string")
    elif t == "integer":
        if not isinstance(value, int) or isinstance(value, bool):
            raise Invalid(f"{path}: expected integer")
    elif t == "number":
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            raise Invalid(f"{path}: expected number")
    elif t == "boolean":
        if not isinstance(value, bool):
            raise Invalid(f"{path}: expected boolean")
    if "enum" in schema and value not in schema["enum"]:
        raise Invalid(f"{path}: {value!r} not in {schema['enum']}")


def make_crd(plural: str, kind: str, group: str = "ktpu.dev", *,
             scope: str = "Namespaced", schema: Mapping | None = None) -> dict:
    crd = {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{group}"},
        "spec": {
            "group": group,
            "scope": scope,
            "names": {"plural": plural, "kind": kind},
            "versions": [{"name": "v1", "served": True,
                          "storage": True}],
        },
    }
    if schema is not None:
        crd["spec"]["versions"][0]["schema"] = {
            "openAPIV3Schema": dict(schema)}
    return crd


def install_crd_support(store) -> None:
    """Creating a CustomResourceDefinition registers the custom resource:
    schema validation on the new table, kind→resource mapping, and
    cluster-scope bookkeeping. (The store serves any table already — a
    CRD's job here is semantics, exactly the apiextensions-apiserver
    split.)"""

    registered: set[str] = set()

    def _crd_for(plural: str) -> dict | None:
        for crd in store._table("customresourcedefinitions").values():
            names = (crd.get("spec") or {}).get("names") or {}
            if names.get("plural") == plural:
                return crd
        return None

    def register(crd: dict) -> None:
        spec = crd.get("spec") or {}
        names = spec.get("names") or {}
        plural = names.get("plural")
        kind = names.get("kind")
        if not plural or not kind:
            raise Invalid("CRD: spec.names.plural and .kind are required")
        # Store-local registration: kind mappings must not leak into other
        # stores in the process, and scope must follow CRD delete/re-create
        # (deregister below), so the process-global KIND_TO_RESOURCE /
        # CLUSTER_SCOPED_RESOURCES stay untouched.
        store.custom_kinds.setdefault(kind, plural)
        if spec.get("scope") == "Cluster":
            store.custom_cluster_scoped.add(plural)
        else:
            store.custom_cluster_scoped.discard(plural)
        if plural in registered:
            return  # one live-reading validator per plural is enough
        registered.add(plural)

        def validate(obj, plural=plural, kind=kind):
            # Read the CURRENT CRD each time: schema updates / delete +
            # re-create take effect immediately, and a deleted CRD stops
            # validating (stale-closure validators would enforce forever).
            live = _crd_for(plural)
            if live is None:
                return
            schema = None
            for v in (live.get("spec") or {}).get("versions") or []:
                if v.get("storage") or schema is None:
                    schema = (v.get("schema") or {}).get("openAPIV3Schema")
            if schema:
                validate_against_schema(obj.get("spec", obj), schema,
                                        path=kind + ".spec"
                                        if "spec" in obj else kind)
        store.register_validator(plural, validate)
        logger.info("CRD registered: %s (kind %s)", plural, kind)

    store.register_mutator("customresourcedefinitions", register,
                           on=("create", "update"))

    def deregister(crd: dict) -> None:
        names = (crd.get("spec") or {}).get("names") or {}
        plural, kind = names.get("plural"), names.get("kind")
        if not plural:
            return  # malformed CRD (never registered) must stay deletable
        if store.custom_kinds.get(kind) == plural:
            del store.custom_kinds[kind]
        store.custom_cluster_scoped.discard(plural)
        # `registered` is deliberately NOT cleared: the live-reading
        # validator self-disables while no CRD exists and re-enables on
        # re-create; dropping the guard would stack a duplicate validator
        # per delete/create cycle. Kind/scope entries (above) are written
        # by register() before its guard, so re-creates still refresh them.

    store.register_mutator("customresourcedefinitions", deregister,
                           on=("delete",))

    # CRDs created before install (store load) register too.
    for crd in list(store._table("customresourcedefinitions").values()):
        try:
            register(crd)
        except StoreError:
            logger.exception("CRD re-registration failed for %s",
                             name_of(crd))
