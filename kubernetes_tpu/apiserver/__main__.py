"""kube-apiserver analog: `python -m kubernetes_tpu.apiserver`.

Serves an MVCC store over BOTH wires — HTTP/1.1+JSON (kubectl,
controllers) and the multiplexed KTPU wire (core components) — with
optional WAL durability (crash recovery on restart), bearer-token authn,
and RBAC loaded from a manifest.

    python -m kubernetes_tpu.apiserver --port 8080 \
        --data-dir /var/lib/ktpu --wire-port 8081

Parity target: cmd/kube-apiserver (SURVEY §2.1).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="ktpu-apiserver", description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--wire-port", type=int, default=0,
                    help="KTPU wire listener port (0 = ephemeral; "
                         "'off' via --no-wire)")
    ap.add_argument("--no-wire", action="store_true")
    from kubernetes_tpu.utils import flags
    ap.add_argument("--data-dir", default=flags.get("KTPU_DATA_DIR"),
                    help="durability directory (WAL + snapshots); "
                         "recovers state on startup when present "
                         "(default: $KTPU_DATA_DIR)")
    ap.add_argument("--fsync", choices=["batch", "always"], default="batch")
    ap.add_argument("--token", action="append", default=[],
                    metavar="TOKEN=USER",
                    help="static bearer token (repeatable)")
    ap.add_argument("--rbac", default=None,
                    help="YAML manifest of ClusterRole/ClusterRoleBinding "
                         "objects enabling RBAC authz")
    ap.add_argument("--audit-log", action="store_true")
    ap.add_argument("--audit-policy", default=None,
                    help="audit.k8s.io/v1 Policy YAML enabling the "
                         "stage-event audit pipeline (levels None/"
                         "Metadata/Request/RequestResponse, first "
                         "matching rule wins)")
    ap.add_argument("--audit-log-path", default=None,
                    help="JSON-lines audit sink with size/age rotation "
                         "(the reference's --audit-log-path)")
    ap.add_argument("--audit-log-maxsize-mb", type=int, default=10,
                    help="rotate the audit log past this size")
    ap.add_argument("--audit-log-maxage-s", type=float, default=None,
                    help="rotate the audit log past this segment age "
                         "in seconds (default: size-only rotation)")
    ap.add_argument("--audit-log-maxbackups", type=int, default=5,
                    help="rotated audit segments kept (.1 newest)")
    ap.add_argument("--audit-webhook-config", default=None,
                    help="YAML {url, batch: {maxSize}, retry: "
                         "{backoff, maxAttempts}} enabling the batching "
                         "audit webhook sink (the reference's "
                         "--audit-webhook-config)")
    ap.add_argument("--trace", action="store_true",
                    help="enable OTel-style request spans")
    return ap


def build_audit_pipeline(args):
    """AuditPipeline from the CLI options, or None when no audit policy
    / sink was asked for. Sink precedence: webhook config > rotated
    file > in-memory (policy with no sink still collects in memory)."""
    if not (args.audit_policy or args.audit_log_path
            or args.audit_webhook_config):
        return None
    from kubernetes_tpu.policy.audit import (
        AuditPipeline,
        AuditPolicy,
        RotatingFileSink,
        WebhookSink,
    )
    policy = AuditPolicy.from_file(args.audit_policy) \
        if args.audit_policy else AuditPolicy.metadata_for_all()
    sink = None
    if args.audit_webhook_config:
        sink = WebhookSink.from_config(args.audit_webhook_config)
    elif args.audit_log_path:
        sink = RotatingFileSink(
            args.audit_log_path,
            max_bytes=args.audit_log_maxsize_mb * 2 ** 20,
            max_age_s=args.audit_log_maxage_s,
            backups=args.audit_log_maxbackups)
    return AuditPipeline(policy, sink=sink)


async def serve(args) -> None:
    from kubernetes_tpu.store import install_core_validation, \
        new_cluster_store
    store = None
    if not args.data_dir:
        # No durability: plain in-memory store. With --data-dir the
        # APIServer owns the whole lifecycle (recover on construction,
        # background flusher/snapshotter, final snapshot on stop).
        store = new_cluster_store()
        install_core_validation(store)

    tokens = {}
    for spec in args.token:
        token, _, user = spec.partition("=")
        if token and user:
            tokens[token] = user

    authorizer = None
    if args.rbac:
        import yaml

        from kubernetes_tpu.apiserver.rbac import RBACAuthorizer
        authorizer = RBACAuthorizer()
        with open(args.rbac) as f:
            for doc in yaml.safe_load_all(f):
                if not doc:
                    continue
                if doc.get("kind") == "ClusterRole":
                    authorizer.add_role(doc)
                elif doc.get("kind") == "ClusterRoleBinding":
                    authorizer.add_binding(doc)

    if args.trace:
        from kubernetes_tpu.utils.tracing import DEFAULT_TRACER
        DEFAULT_TRACER.enabled = True

    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.apiserver.wire import WireServer
    api = APIServer(store, host=args.host, port=args.port,
                    bearer_tokens=tokens, authorizer=authorizer,
                    audit_log=args.audit_log,
                    audit=build_audit_pipeline(args),
                    data_dir=args.data_dir, fsync=args.fsync)
    store = api.store
    await api.start()
    wire = None
    if not args.no_wire:
        wire = WireServer.for_apiserver(api, host=args.host,
                                        port=args.wire_port)
        await wire.start()
        logging.info("wire listening on %s", wire.target)
    logging.info("apiserver listening on %s", api.url)

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    await stop.wait()
    if wire is not None:
        await wire.stop()
    await api.stop()  # owns the durability stop + final snapshot
    store.stop()


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    args = build_parser().parse_args(argv)
    asyncio.run(serve(args))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
