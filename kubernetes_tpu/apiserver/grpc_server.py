"""gRPC + protobuf wire for the store (SURVEY §5.8's "gRPC variant").

Parity notes: the reference's core components speak protobuf over HTTP/2
(`application/vnd.kubernetes.protobuf`), with objects carried in a
`runtime.Unknown` envelope — TypeMeta plus raw payload bytes. This wire
is exactly that shape (`Unknown{api_version, kind, raw, content_type}`,
raw = JSON bytes), over grpc.aio. The service surface mirrors
`storage.Interface`: Get/List/Create/Update/Delete/Subresource unary
calls plus a server-streaming Watch with BOOKMARK frames and
OUT_OF_RANGE for expired resourceVersions (the 410 analog).

`GRPCRemoteStore` is MVCCStore-shaped: informers/controllers/scheduler
run over it unchanged, like the HTTP RemoteStore.
"""

from __future__ import annotations

import asyncio
import json
import logging
import sys
from pathlib import Path

import grpc

sys.path.insert(0, str(Path(__file__).parent / "proto"))
import ktpu_pb2  # noqa: E402  (protoc --python_out output)

from kubernetes_tpu.api.labels import (  # noqa: E402
    Selector,
    parse_selector,
    selector_to_string,
)
from kubernetes_tpu.store.mvcc import (  # noqa: E402
    AlreadyExists,
    Conflict,
    Expired,
    Invalid,
    MVCCStore,
    NotFound,
    StoreError,
)

logger = logging.getLogger(__name__)

_SERVICE = "ktpu.Store"

_CODE_OF = {
    NotFound: grpc.StatusCode.NOT_FOUND,
    AlreadyExists: grpc.StatusCode.ALREADY_EXISTS,
    Conflict: grpc.StatusCode.ABORTED,
    Invalid: grpc.StatusCode.INVALID_ARGUMENT,
    Expired: grpc.StatusCode.OUT_OF_RANGE,
}
_ERR_OF = {v: k for k, v in _CODE_OF.items()}


def _wrap(obj: dict) -> "ktpu_pb2.Unknown":
    return ktpu_pb2.Unknown(
        api_version=obj.get("apiVersion", ""),
        kind=obj.get("kind", ""),
        raw=json.dumps(obj).encode(),
        content_type="application/json")


def _unwrap(u: "ktpu_pb2.Unknown") -> dict:
    return json.loads(u.raw.decode()) if u.raw else {}


def _abort_code(e: StoreError) -> grpc.StatusCode:
    for cls, code in _CODE_OF.items():
        if isinstance(e, cls):
            return code
    return grpc.StatusCode.INTERNAL


class StoreService:
    """grpc.aio service over one MVCCStore."""

    def __init__(self, store: MVCCStore):
        self.store = store

    async def Get(self, request, context):
        try:
            obj = await self.store.get(request.resource, request.key)
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))
        return _wrap(obj)

    async def List(self, request, context):
        sel = parse_selector(request.label_selector) \
            if request.label_selector else None
        try:
            lst = await self.store.list(
                request.resource,
                namespace=request.namespace or None,
                selector=sel, limit=request.limit,
                continue_key=request.continue_key or None)
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))
        return ktpu_pb2.ListResponse(
            items=[_wrap(o) for o in lst.items],
            resource_version=str(lst.resource_version))

    async def Create(self, request, context):
        try:
            obj = await self.store.create(
                request.resource, _unwrap(request.object))
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))
        return _wrap(obj)

    async def Update(self, request, context):
        try:
            obj = await self.store.update(
                request.resource, _unwrap(request.object))
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))
        return _wrap(obj)

    async def Delete(self, request, context):
        try:
            obj = await self.store.delete(
                request.resource, request.key, uid=request.uid or None)
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))
        return _wrap(obj)

    async def Subresource(self, request, context):
        try:
            obj = await self.store.subresource(
                request.resource, request.key, request.subresource,
                _unwrap(request.body))
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))
        return _wrap(obj)

    async def Watch(self, request, context):
        sel = parse_selector(request.label_selector) \
            if request.label_selector else None
        rv = int(request.resource_version) \
            if request.resource_version else 0
        try:
            async for ev in await self.store.watch(
                    request.resource, resource_version=rv, selector=sel):
                yield ktpu_pb2.WatchEvent(
                    type=ev.type, object=_wrap(ev.object))
        except Expired as e:
            await context.abort(grpc.StatusCode.OUT_OF_RANGE, str(e))
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))


def _handlers(svc: StoreService) -> grpc.GenericRpcHandler:
    def uu(fn, req_cls, resp_cls=ktpu_pb2.Unknown):
        return grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString)

    method_handlers = {
        "Get": uu(svc.Get, ktpu_pb2.GetRequest),
        "List": uu(svc.List, ktpu_pb2.ListRequest, ktpu_pb2.ListResponse),
        "Create": uu(svc.Create, ktpu_pb2.CreateRequest),
        "Update": uu(svc.Update, ktpu_pb2.UpdateRequest),
        "Delete": uu(svc.Delete, ktpu_pb2.DeleteRequest),
        "Subresource": uu(svc.Subresource, ktpu_pb2.SubresourceRequest),
        "Watch": grpc.unary_stream_rpc_method_handler(
            svc.Watch,
            request_deserializer=ktpu_pb2.WatchRequest.FromString,
            response_serializer=ktpu_pb2.WatchEvent.SerializeToString),
    }
    return grpc.method_handlers_generic_handler(_SERVICE, method_handlers)


class GRPCAPIServer:
    """Serve one MVCCStore over gRPC (the §5.8 wire option)."""

    def __init__(self, store: MVCCStore, host: str = "127.0.0.1",
                 port: int = 0):
        self.store = store
        self.host = host
        self.port = port
        self._server: grpc.aio.Server | None = None

    @property
    def target(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers(
            (_handlers(StoreService(self.store)),))
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        await self._server.start()

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=0.2)
            self._server = None


class _ListResult:
    __slots__ = ("items", "resource_version")

    def __init__(self, items, rv):
        self.items = items
        self.resource_version = rv


class _Event:
    __slots__ = ("type", "object")

    def __init__(self, type_, obj):
        self.type = type_
        self.object = obj


def _map_rpc_error(e: grpc.aio.AioRpcError) -> StoreError:
    cls = _ERR_OF.get(e.code(), StoreError)
    return cls(e.details() or str(e.code()))


class GRPCRemoteStore:
    """MVCCStore-shaped client over the gRPC wire."""

    def __init__(self, target: str):
        self.target = target
        self._channel = grpc.aio.insecure_channel(target)

    def _uu(self, method: str, req, resp_cls=ktpu_pb2.Unknown):
        return self._channel.unary_unary(
            f"/{_SERVICE}/{method}",
            request_serializer=type(req).SerializeToString,
            response_deserializer=resp_cls.FromString)(req)

    async def close(self) -> None:
        await self._channel.close()

    async def get(self, resource: str, key: str) -> dict:
        try:
            return _unwrap(await self._uu(
                "Get", ktpu_pb2.GetRequest(resource=resource, key=key)))
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e) from e

    async def list(self, resource: str, namespace: str | None = None,
                   selector: Selector | None = None, limit: int = 0,
                   continue_key: str | None = None) -> _ListResult:
        sel = selector_to_string(selector) if selector else ""
        try:
            resp = await self._uu(
                "List",
                ktpu_pb2.ListRequest(
                    resource=resource, namespace=namespace or "",
                    label_selector=sel or "", limit=limit,
                    continue_key=continue_key or ""),
                ktpu_pb2.ListResponse)
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e) from e
        return _ListResult([_unwrap(u) for u in resp.items],
                           int(resp.resource_version))

    async def create(self, resource: str, obj: dict, **_kw) -> dict:
        try:
            return _unwrap(await self._uu("Create", ktpu_pb2.CreateRequest(
                resource=resource, object=_wrap(dict(obj)))))
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e) from e

    async def update(self, resource: str, obj: dict, **_kw) -> dict:
        try:
            return _unwrap(await self._uu("Update", ktpu_pb2.UpdateRequest(
                resource=resource, object=_wrap(dict(obj)))))
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e) from e

    async def delete(self, resource: str, key: str,
                     uid: str | None = None) -> dict:
        try:
            return _unwrap(await self._uu("Delete", ktpu_pb2.DeleteRequest(
                resource=resource, key=key, uid=uid or "")))
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e) from e

    async def subresource(self, resource: str, key: str, sub: str,
                          body: dict) -> dict:
        try:
            return _unwrap(await self._uu(
                "Subresource", ktpu_pb2.SubresourceRequest(
                    resource=resource, key=key, subresource=sub,
                    body=_wrap(dict(body)))))
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e) from e

    async def guaranteed_update(self, resource: str, key: str, mutate,
                                max_retries: int = 16,
                                return_copy: bool = True) -> dict | None:
        """Client-side CAS loop, like the HTTP RemoteStore."""
        for _ in range(max_retries):
            current = await self.get(resource, key)
            updated = mutate(current)
            if updated is None:
                if not return_copy:
                    return None
                return await self.get(resource, key)
            try:
                out = await self.update(resource, updated)
                return out if return_copy else None
            except Conflict:
                continue
        raise Conflict(f"{resource} {key!r}: too many conflicts")

    async def watch(self, resource: str, resource_version: int | None = None,
                    selector: Selector | None = None):
        """Async iterator of events; Expired raised on 410-equivalents so
        the informer relists, matching the store contract."""
        sel = selector_to_string(selector) if selector else ""
        call = self._channel.unary_stream(
            f"/{_SERVICE}/Watch",
            request_serializer=ktpu_pb2.WatchRequest.SerializeToString,
            response_deserializer=ktpu_pb2.WatchEvent.FromString,
        )(ktpu_pb2.WatchRequest(
            resource=resource,
            resource_version=str(resource_version)
            if resource_version is not None else "",
            label_selector=sel or ""))

        async def gen():
            try:
                async for ev in call:
                    yield _Event(ev.type, _unwrap(ev.object))
            except grpc.aio.AioRpcError as e:
                raise _map_rpc_error(e) from e
            except asyncio.CancelledError:
                call.cancel()
                raise
        return gen()
