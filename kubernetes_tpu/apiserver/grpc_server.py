"""gRPC + protobuf wire for the store (SURVEY §5.8's "gRPC variant").

Parity notes: the reference's core components speak protobuf over HTTP/2
(`application/vnd.kubernetes.protobuf`), with objects carried in a
`runtime.Unknown` envelope — TypeMeta plus raw payload bytes. This wire
is exactly that shape (`Unknown{api_version, kind, raw, content_type}`,
raw = JSON bytes), over grpc.aio. The service surface mirrors
`storage.Interface`: Get/List/Create/Update/Delete/Subresource unary
calls plus a server-streaming Watch with BOOKMARK frames and
OUT_OF_RANGE for expired resourceVersions (the 410 analog).

`GRPCRemoteStore` is MVCCStore-shaped: informers/controllers/scheduler
run over it unchanged, like the HTTP RemoteStore.
"""

from __future__ import annotations

import asyncio
import contextvars
import json
import logging
import sys
from pathlib import Path
from typing import Mapping

import grpc

sys.path.insert(0, str(Path(__file__).parent / "proto"))
import ktpu_pb2  # noqa: E402  (protoc --python_out output)

from kubernetes_tpu.api.labels import (  # noqa: E402
    Selector,
    parse_selector,
    selector_to_string,
)
from kubernetes_tpu.store.mvcc import (  # noqa: E402
    AlreadyExists,
    Conflict,
    Expired,
    Invalid,
    MVCCStore,
    NotFound,
    StoreError,
)
from kubernetes_tpu.utils.tracing import stamp_traceparent  # noqa: E402

logger = logging.getLogger(__name__)

_SERVICE = "ktpu.Store"

_CODE_OF = {
    NotFound: grpc.StatusCode.NOT_FOUND,
    AlreadyExists: grpc.StatusCode.ALREADY_EXISTS,
    Conflict: grpc.StatusCode.ABORTED,
    Invalid: grpc.StatusCode.INVALID_ARGUMENT,
    Expired: grpc.StatusCode.OUT_OF_RANGE,
}
_ERR_OF = {v: k for k, v in _CODE_OF.items()}


def _wrap(obj: dict) -> "ktpu_pb2.Unknown":
    return ktpu_pb2.Unknown(
        api_version=obj.get("apiVersion", ""),
        kind=obj.get("kind", ""),
        raw=json.dumps(obj).encode(),
        content_type="application/json")


def _unwrap(u: "ktpu_pb2.Unknown") -> dict:
    return json.loads(u.raw.decode()) if u.raw else {}


def _abort_code(e: StoreError) -> grpc.StatusCode:
    for cls, code in _CODE_OF.items():
        if isinstance(e, cls):
            return code
    return grpc.StatusCode.INTERNAL


#: (user, groups) of the current RPC, set by AuthInterceptor's wrapped
#: handler in the same task context the service method runs in — how the
#: admission chain learns the caller identity without widening the
#: service signatures.
_CALLER: contextvars.ContextVar = contextvars.ContextVar(
    "ktpu_grpc_caller", default=None)


class StoreService:
    """grpc.aio service over one MVCCStore. With an admission chain
    attached, writes run mutating webhooks → expression policies →
    validating webhooks exactly like the HTTP and KTPU wires."""

    def __init__(self, store: MVCCStore, admission=None):
        self.store = store
        self.admission = admission

    async def _admit(self, obj: dict, resource: str, op: str) -> dict:
        if self.admission is None:
            return obj
        caller = _CALLER.get() or ("system:anonymous", [])
        return await self.admission.admit(
            obj, resource, op, user=caller[0], groups=caller[1])

    async def Get(self, request, context):
        try:
            obj = await self.store.get(request.resource, request.key)
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))
        return _wrap(obj)

    async def List(self, request, context):
        sel = parse_selector(request.label_selector) \
            if request.label_selector else None
        try:
            # Served from the watch-cache tier (store/cacher.py) like the
            # other two wires. Exact-RV reads need no new proto field:
            # the continue token carries its own RV pin ("<rv>:<key>",
            # "<rv>:" for a pinned first page), so snapshot-consistent
            # pagination round-trips through ListRequest.continue_key.
            lst = await self.store.list(
                request.resource,
                namespace=request.namespace or None,
                selector=sel, limit=request.limit,
                continue_key=request.continue_key or None,
                copy=False)  # encode-only: wrapped before return
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))
        return ktpu_pb2.ListResponse(
            items=[_wrap(o) for o in lst.items],
            resource_version=str(lst.resource_version))

    async def Create(self, request, context):
        try:
            obj = _unwrap(request.object)
            if request.resource == "pods":
                # Carry the RPC's trace across the informer/queue
                # boundary (no-op outside a span).
                stamp_traceparent(obj)
            obj = await self._admit(obj, request.resource, "create")
            obj = await self.store.create(request.resource, obj)
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))
        return _wrap(obj)

    async def Update(self, request, context):
        try:
            obj = await self._admit(
                _unwrap(request.object), request.resource, "update")
            obj = await self.store.update(request.resource, obj)
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))
        return _wrap(obj)

    async def Delete(self, request, context):
        try:
            if self.admission is not None:
                current = await self.store.get(
                    request.resource, request.key)
                await self._admit(current, request.resource, "delete")
            obj = await self.store.delete(
                request.resource, request.key, uid=request.uid or None)
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))
        return _wrap(obj)

    async def Subresource(self, request, context):
        try:
            obj = await self.store.subresource(
                request.resource, request.key, request.subresource,
                _unwrap(request.body))
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))
        return _wrap(obj)

    async def Watch(self, request, context):
        sel = parse_selector(request.label_selector) \
            if request.label_selector else None
        rv = int(request.resource_version) \
            if request.resource_version else 0
        try:
            async for ev in await self.store.watch(
                    request.resource, resource_version=rv, selector=sel):
                yield ktpu_pb2.WatchEvent(
                    type=ev.type, object=_wrap(ev.object),
                    rv=str(ev.rv))
        except Expired as e:
            await context.abort(grpc.StatusCode.OUT_OF_RANGE, str(e))
        except StoreError as e:
            await context.abort(_abort_code(e), str(e))


_VERB_OF_METHOD = {"Get": "get", "List": "list", "Create": "create",
                   "Update": "update", "Delete": "delete",
                   "Subresource": "update", "Watch": "watch"}


class AuthInterceptor(grpc.aio.ServerInterceptor):
    """The gRPC analog of the apiserver handler chain (§3.2): authn
    (authorization metadata) → audit stage events → impersonation
    (impersonate-user metadata, RBAC `impersonate`-gated) → authz →
    service method. Wraps the resolved method handler so the audit
    events see the DESERIALIZED request (resource/key) and the final
    status code."""

    def __init__(self, owner: "GRPCAPIServer"):
        self.owner = owner

    def _authn(self, md: Mapping) -> str | None:
        owner = self.owner
        auth = md.get("authorization", "")
        if auth.startswith("Bearer ") and owner.bearer_tokens is not None:
            user = owner.bearer_tokens.get(auth[len("Bearer "):])
            if user is None:
                return None  # invalid token → UNAUTHENTICATED
            return user
        return "system:anonymous"

    async def intercept_service(self, continuation, details):
        handler = await continuation(details)
        owner = self.owner
        tracer = owner.tracer
        tracing = tracer is not None and tracer.enabled
        if handler is None or (owner.bearer_tokens is None
                               and owner.authorizer is None
                               and owner.audit is None
                               and not tracing):
            return handler  # chain disabled: raw service
        md = {k: v for k, v in (details.invocation_metadata or ())}
        method = details.method.rsplit("/", 1)[-1]
        verb = _VERB_OF_METHOD.get(method, method.lower())
        auth_user = self._authn(md)
        target = md.get("impersonate-user") or None
        fail: tuple[grpc.StatusCode, str] | None = None
        user = auth_user
        if auth_user is None:
            fail = (grpc.StatusCode.UNAUTHENTICATED, "invalid token")
        elif target:
            if owner.authorizer is not None and \
                    not owner.authorizer.allowed(
                        auth_user, "impersonate", "users",
                        groups=owner.groups_for(auth_user)):
                fail = (grpc.StatusCode.PERMISSION_DENIED,
                        f'user "{auth_user}" cannot impersonate user '
                        f'"{target}"')
            else:
                user = target

        def begin_audit(request):
            if owner.audit is None:
                return None
            resource = getattr(request, "resource", "") or ""
            if not resource:
                return None
            key = getattr(request, "key", "") or ""
            ns, _, name = key.rpartition("/")
            # Invalid-token requests still audit (as anonymous): the
            # denials are exactly what the pipeline exists to record.
            audit_user = auth_user or "system:anonymous"
            groups = owner.groups_for(audit_user)
            rule = owner.audit.policy.rule_for(
                user=audit_user, groups=groups, verb=verb,
                resource=resource, namespace=ns or None)
            if rule is None or rule.get("level", "None") == "None":
                return None  # unaudited: skip the payload parse below
            if not name:
                # Create/Update carry the identity inside the
                # runtime.Unknown envelope, not a key field — parsed
                # only for requests the policy actually audits (the
                # service re-parses via _unwrap; doubling that cost on
                # every unaudited write would tax the wire's whole
                # point).
                unknown = getattr(request, "object", None)
                if unknown is not None and unknown.raw:
                    try:
                        meta = (json.loads(unknown.raw).get("metadata")
                                or {})
                        name = meta.get("name", "")
                        ns = meta.get("namespace", "")
                    except (ValueError, json.JSONDecodeError):
                        pass
            return owner.audit.begin(
                user=audit_user, groups=groups, verb=verb,
                resource=resource, namespace=ns or None,
                name=name or None, rule=rule)

        def end_audit(actx, code: int):
            if actx is not None:
                owner.audit.response_complete(
                    actx, code=code,
                    impersonated_user=user
                    if user and user != auth_user else None)

        def check_authz(request) -> str | None:
            resource = getattr(request, "resource", "") or ""
            if owner.authorizer is None or not resource:
                return None
            if not owner.authorizer.allowed(
                    user, verb, resource, groups=owner.groups_for(user)):
                return f'user "{user}" cannot {verb} resource ' \
                       f'"{resource}"'
            return None

        if handler.unary_unary is not None:
            inner = handler.unary_unary

            async def uu(request, context):
                if tracing:
                    # gRPC-metadata traceparent (the interceptor-chain
                    # analog of the HTTP traceparent header): the RPC's
                    # server span parents to the caller's.
                    resource = getattr(request, "resource", "") or "misc"
                    with tracer.span(f"grpc.{verb}.{resource}",
                                     traceparent=md.get("traceparent"),
                                     user=user or "system:anonymous"):
                        return await uu_chain(request, context)
                return await uu_chain(request, context)

            async def uu_chain(request, context):
                actx = begin_audit(request)
                if fail is not None:
                    # authn/impersonation denials are audited too — the
                    # HTTP wire records its 401/403s, so must this one.
                    end_audit(actx, _GRPC_AUDIT_CODE.get(fail[0], 500))
                    await context.abort(*fail)
                denied = check_authz(request)
                if denied is not None:
                    end_audit(actx, 403)
                    await context.abort(
                        grpc.StatusCode.PERMISSION_DENIED, denied)
                token = _CALLER.set((user, owner.groups_for(user)))
                try:
                    resp = await inner(request, context)
                except grpc.aio.AbortError:
                    end_audit(actx, _GRPC_AUDIT_CODE.get(
                        context.code(), 500))
                    raise
                except Exception:
                    # Non-StoreError bug: gRPC will return UNKNOWN; the
                    # audit trail still gets its ResponseComplete (the
                    # HTTP wire records these as 500 the same way).
                    end_audit(actx, 500)
                    raise
                finally:
                    _CALLER.reset(token)
                end_audit(actx, 200)
                return resp

            return grpc.unary_unary_rpc_method_handler(
                uu, request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer)

        if handler.unary_stream is not None:
            inner_stream = handler.unary_stream

            async def us(request, context):
                if tracing:
                    resource = getattr(request, "resource", "") or "misc"
                    with tracer.span(f"grpc.{verb}.{resource}",
                                     traceparent=md.get("traceparent"),
                                     user=user or "system:anonymous"):
                        async for item in us_chain(request, context):
                            yield item
                    return
                async for item in us_chain(request, context):
                    yield item

            async def us_chain(request, context):
                actx = begin_audit(request)
                if fail is not None:
                    end_audit(actx, _GRPC_AUDIT_CODE.get(fail[0], 500))
                    await context.abort(*fail)
                denied = check_authz(request)
                if denied is not None:
                    end_audit(actx, 403)
                    await context.abort(
                        grpc.StatusCode.PERMISSION_DENIED, denied)
                end_audit(actx, 200)  # long-running: accepted = complete
                async for item in inner_stream(request, context):
                    yield item

            return grpc.unary_stream_rpc_method_handler(
                us, request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer)

        return handler


_GRPC_AUDIT_CODE = {
    grpc.StatusCode.NOT_FOUND: 404,
    grpc.StatusCode.ALREADY_EXISTS: 409,
    grpc.StatusCode.ABORTED: 409,
    grpc.StatusCode.INVALID_ARGUMENT: 422,
    grpc.StatusCode.OUT_OF_RANGE: 410,
    grpc.StatusCode.PERMISSION_DENIED: 403,
    grpc.StatusCode.UNAUTHENTICATED: 401,
}


def _handlers(svc: StoreService) -> grpc.GenericRpcHandler:
    def uu(fn, req_cls, resp_cls=ktpu_pb2.Unknown):
        return grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString)

    method_handlers = {
        "Get": uu(svc.Get, ktpu_pb2.GetRequest),
        "List": uu(svc.List, ktpu_pb2.ListRequest, ktpu_pb2.ListResponse),
        "Create": uu(svc.Create, ktpu_pb2.CreateRequest),
        "Update": uu(svc.Update, ktpu_pb2.UpdateRequest),
        "Delete": uu(svc.Delete, ktpu_pb2.DeleteRequest),
        "Subresource": uu(svc.Subresource, ktpu_pb2.SubresourceRequest),
        "Watch": grpc.unary_stream_rpc_method_handler(
            svc.Watch,
            request_deserializer=ktpu_pb2.WatchRequest.FromString,
            response_serializer=ktpu_pb2.WatchEvent.SerializeToString),
    }
    return grpc.method_handlers_generic_handler(_SERVICE, method_handlers)


class GRPCAPIServer:
    """Serve one MVCCStore over gRPC (the §5.8 wire option).

    With any of `bearer_tokens` / `authorizer` / `audit` configured, the
    AuthInterceptor chain (authn → audit → impersonation → authz) runs in
    front of the service — the same policy objects the HTTP and KTPU
    wires share."""

    def __init__(self, store: MVCCStore, host: str = "127.0.0.1",
                 port: int = 0, *,
                 bearer_tokens: Mapping[str, str] | None = None,
                 user_groups: Mapping[str, list] | None = None,
                 authorizer=None, audit=None, admission=None,
                 tracer=None):
        self.store = store
        self.host = host
        self.port = port
        #: WebhookAdmission (webhooks + expression policies) or None.
        self.admission = admission
        #: None = authn disabled (anonymous); {} would reject every token.
        self.bearer_tokens = dict(bearer_tokens) \
            if bearer_tokens is not None else None
        self.user_groups = {u: list(g) for u, g in
                            (user_groups or {}).items()}
        self.authorizer = authorizer
        self.audit = audit
        #: OTel-style per-RPC spans (§5.1), same process tracer as the
        #: other wires by default.
        if tracer is None:
            from kubernetes_tpu.utils.tracing import DEFAULT_TRACER
            tracer = DEFAULT_TRACER
        self.tracer = tracer
        self._server: grpc.aio.Server | None = None

    def groups_for(self, user: str) -> list:
        groups = list(self.user_groups.get(user, ()))
        groups.append("system:unauthenticated"
                      if user == "system:anonymous"
                      else "system:authenticated")
        return groups

    @property
    def target(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self._server = grpc.aio.server(
            interceptors=(AuthInterceptor(self),))
        self._server.add_generic_rpc_handlers(
            (_handlers(StoreService(self.store, self.admission)),))
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        await self._server.start()

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=0.2)
            self._server = None


class _ListResult:
    __slots__ = ("items", "resource_version", "cont")

    def __init__(self, items, rv, cont=None):
        self.items = items
        self.resource_version = rv
        self.cont = cont


class _Event:
    __slots__ = ("type", "object", "rv")

    def __init__(self, type_, obj, rv=0):
        self.type = type_
        self.object = obj
        self.rv = rv


def _map_rpc_error(e: grpc.aio.AioRpcError) -> StoreError:
    cls = _ERR_OF.get(e.code(), StoreError)
    return cls(e.details() or str(e.code()))


class GRPCRemoteStore:
    """MVCCStore-shaped client over the gRPC wire."""

    def __init__(self, target: str, *, token: str | None = None,
                 impersonate: str | None = None):
        self.target = target
        self._channel = grpc.aio.insecure_channel(target)
        md = []
        if token:
            md.append(("authorization", f"Bearer {token}"))
        if impersonate:
            # The interceptor-chain impersonation field (client-go
            # ImpersonationConfig analog on this wire).
            md.append(("impersonate-user", impersonate))
        self._metadata = tuple(md) or None

    def _md(self):
        """Per-call metadata: the static auth/impersonation pairs plus a
        traceparent when the call is issued inside a span (client-go's
        otelgrpc interceptor analog)."""
        from kubernetes_tpu.utils.tracing import DEFAULT_TRACER
        if DEFAULT_TRACER.enabled:
            tp = DEFAULT_TRACER.current_traceparent()
            if tp:
                return (*(self._metadata or ()), ("traceparent", tp))
        return self._metadata

    def _uu(self, method: str, req, resp_cls=ktpu_pb2.Unknown):
        return self._channel.unary_unary(
            f"/{_SERVICE}/{method}",
            request_serializer=type(req).SerializeToString,
            response_deserializer=resp_cls.FromString)(
                req, metadata=self._md())

    async def close(self) -> None:
        await self._channel.close()

    async def get(self, resource: str, key: str) -> dict:
        try:
            return _unwrap(await self._uu(
                "Get", ktpu_pb2.GetRequest(resource=resource, key=key)))
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e) from e

    async def list(self, resource: str, namespace: str | None = None,
                   selector: Selector | None = None, limit: int = 0,
                   continue_key: str | None = None, *,
                   resource_version: int | None = None,
                   resource_version_match: str | None = None,
                   **_kw) -> _ListResult:
        sel = selector_to_string(selector) if selector else ""
        if resource_version and resource_version_match == "Exact" \
                and not continue_key:
            # Exact-RV LIST without a proto field: the pinned continue
            # token ("<rv>:") asks the server's watch-cache tier for the
            # snapshot at that RV from the first page on.
            continue_key = f"{resource_version}:"
        try:
            resp = await self._uu(
                "List",
                ktpu_pb2.ListRequest(
                    resource=resource, namespace=namespace or "",
                    label_selector=sel or "", limit=limit,
                    continue_key=continue_key or ""),
                ktpu_pb2.ListResponse)
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e) from e
        items = [_unwrap(u) for u in resp.items]
        cont = None
        if limit and len(items) >= limit and items:
            # ListResponse carries no token field; rebuild the pinned one
            # from the snapshot RV + last key (the server resumes
            # strictly after it, at the same snapshot).
            meta = items[-1].get("metadata") or {}
            last = f"{meta['namespace']}/{meta['name']}" \
                if meta.get("namespace") else meta.get("name", "")
            cont = f"{int(resp.resource_version)}:{last}"
        return _ListResult(items, int(resp.resource_version), cont)

    async def create(self, resource: str, obj: dict, **_kw) -> dict:
        try:
            return _unwrap(await self._uu("Create", ktpu_pb2.CreateRequest(
                resource=resource, object=_wrap(dict(obj)))))
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e) from e

    async def update(self, resource: str, obj: dict, **_kw) -> dict:
        try:
            return _unwrap(await self._uu("Update", ktpu_pb2.UpdateRequest(
                resource=resource, object=_wrap(dict(obj)))))
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e) from e

    async def delete(self, resource: str, key: str,
                     uid: str | None = None) -> dict:
        try:
            return _unwrap(await self._uu("Delete", ktpu_pb2.DeleteRequest(
                resource=resource, key=key, uid=uid or "")))
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e) from e

    async def subresource(self, resource: str, key: str, sub: str,
                          body: dict) -> dict:
        try:
            return _unwrap(await self._uu(
                "Subresource", ktpu_pb2.SubresourceRequest(
                    resource=resource, key=key, subresource=sub,
                    body=_wrap(dict(body)))))
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e) from e

    async def guaranteed_update(self, resource: str, key: str, mutate,
                                max_retries: int = 16,
                                return_copy: bool = True) -> dict | None:
        """Client-side CAS loop, like the HTTP RemoteStore."""
        for _ in range(max_retries):
            current = await self.get(resource, key)
            updated = mutate(current)
            if updated is None:
                if not return_copy:
                    return None
                return await self.get(resource, key)
            try:
                out = await self.update(resource, updated)
                return out if return_copy else None
            except Conflict:
                continue
        raise Conflict(f"{resource} {key!r}: too many conflicts")

    async def watch(self, resource: str, resource_version: int | None = None,
                    selector: Selector | None = None):
        """Async iterator of events; Expired raised on 410-equivalents so
        the informer relists, matching the store contract."""
        sel = selector_to_string(selector) if selector else ""
        call = self._channel.unary_stream(
            f"/{_SERVICE}/Watch",
            request_serializer=ktpu_pb2.WatchRequest.SerializeToString,
            response_deserializer=ktpu_pb2.WatchEvent.FromString,
        )(ktpu_pb2.WatchRequest(
            resource=resource,
            resource_version=str(resource_version)
            if resource_version is not None else "",
            label_selector=sel or ""), metadata=self._md())

        async def gen():
            try:
                async for ev in call:
                    obj = _unwrap(ev.object)
                    # rv rides its own field; old servers omit it, so
                    # fall back to the object's stamped metadata.
                    rv = int(ev.rv) if ev.rv else int(
                        (obj.get("metadata") or {})
                        .get("resourceVersion") or 0)
                    yield _Event(ev.type, obj, rv)
            except grpc.aio.AioRpcError as e:
                raise _map_rpc_error(e) from e
            except asyncio.CancelledError:
                call.cancel()
                raise
        return gen()
