"""RemoteStore: the client-go analog — MVCCStore's interface over HTTP.

Parity target: client-go `rest/request.go` + the clientset surface. Every
in-process consumer (informers, controllers, the scheduler's DefaultBinder)
takes a "store" duck-typed to MVCCStore; RemoteStore implements that duck
type against an APIServer, so components gain a remote mode with no changes:

- list/watch with label selectors, resourceVersion resume, 410 → Expired
  (the informer's relist path), BOOKMARK frames
- create/get/update/delete with kube Status error mapping
- guaranteed_update as a client-side CAS retry loop
  (client-go util/retry.RetryOnConflict)
- subresource POST (binding)
"""

from __future__ import annotations

import asyncio
import copy
import json
import logging
from typing import AsyncIterator, Callable, Mapping

import aiohttp

from kubernetes_tpu.api.labels import (
    Selector,
    field_selector_to_string,
    selector_to_string,
)
from kubernetes_tpu.api.meta import namespaced_name
from kubernetes_tpu.store.mvcc import (
    AlreadyExists,
    Conflict,
    Event,
    Expired,
    Invalid,
    ListResult,
    NotFound,
    StoreError,
)
from kubernetes_tpu.api.meta import (
    CLUSTER_SCOPED_RESOURCES as CLUSTER_SCOPED,
)
from kubernetes_tpu.apiserver.server import PROTOBUF_CT

logger = logging.getLogger(__name__)

_REASON_TO_EXC = {
    "NotFound": NotFound,
    "AlreadyExists": AlreadyExists,
    "Conflict": Conflict,
    "Invalid": Invalid,
    "Expired": Expired,
    "Gone": Expired,
}


def _raise_for_status(status: int, body: dict | str) -> None:
    if status < 400:
        return
    reason, message = "", str(body)
    if isinstance(body, dict):
        reason = body.get("reason", "")
        message = body.get("message", message)
    exc = _REASON_TO_EXC.get(reason)
    if exc is None:
        exc = {404: NotFound, 409: Conflict, 410: Expired,
               422: Invalid}.get(status, StoreError)
    raise exc(message)


class RemoteStore:
    """MVCCStore-shaped client for an APIServer at `base_url`."""

    def __init__(self, base_url: str, *, token: str | None = None,
                 user_agent: str = "kubernetes-tpu-client",
                 protobuf: bool = False, impersonate: str | None = None):
        self.base_url = base_url.rstrip("/")
        self._headers = {"User-Agent": user_agent}
        if impersonate:
            # client-go ImpersonationConfig: every request asks the server
            # to run as this user (RBAC `impersonate` verb gates it).
            self._headers["Impersonate-User"] = impersonate
        #: Negotiate the runtime.Unknown protobuf envelope for single
        #: objects (the reference's application/vnd.kubernetes.protobuf
        #: wire between core components); lists/watches stay JSON.
        self.protobuf = protobuf
        if protobuf:
            self._headers["Accept"] = f"{PROTOBUF_CT}, application/json"
        if token:
            self._headers["Authorization"] = f"Bearer {token}"
        self._session: aiohttp.ClientSession | None = None
        # Discovery-learned kind/scope maps (refresh_discovery). CRD
        # registration is store-local server-side, so a remote client must
        # LEARN custom scopes from /api/v1 rather than share process
        # globals; until fetched, built-ins apply.
        self._disc_kinds: dict[str, str] | None = None
        self._disc_cluster_scoped: set[str] = set()

    def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(headers=self._headers)
        return self._session

    @staticmethod
    def _trace_headers() -> dict | None:
        """W3C traceparent propagation: a write issued inside a span
        (e.g. the binding POST inside scheduler.bind) parents the
        server-side request span to it."""
        from kubernetes_tpu.utils.tracing import DEFAULT_TRACER
        if not DEFAULT_TRACER.enabled:
            return None
        tp = DEFAULT_TRACER.current_traceparent()
        return {"traceparent": tp} if tp else None

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    # alias so factory.stop()/store.stop() call sites can treat either store
    def stop(self) -> None:
        if self._session is not None and not self._session.closed:
            asyncio.ensure_future(self._session.close())

    # -- discovery ---------------------------------------------------------

    async def refresh_discovery(self) -> None:
        """Fetch /api/v1 (APIResourceList) and cache kind↔resource +
        scope for every server-known resource, CRDs included — the
        kubectl RESTMapper pattern. Safe to skip: built-ins then apply."""
        async with self._sess().get(f"{self.base_url}/api/v1") as r:
            if r.status != 200:
                return
            doc = await r.json()
        kinds: dict[str, str] = {}
        scoped: set[str] = set()
        for res in doc.get("resources") or []:
            name, kind = res.get("name"), res.get("kind")
            if not name or not kind:
                continue
            kinds[kind] = name
            if not res.get("namespaced", True):
                scoped.add(name)
        self._disc_kinds = kinds
        self._disc_cluster_scoped = scoped

    def is_cluster_scoped(self, resource: str) -> bool:
        if self._disc_kinds is not None:
            return resource in self._disc_cluster_scoped
        return resource in CLUSTER_SCOPED

    def resource_for_kind(self, kind: str) -> str | None:
        if self._disc_kinds is not None and kind in self._disc_kinds:
            return self._disc_kinds[kind]
        from kubernetes_tpu.api.meta import KIND_TO_RESOURCE
        return KIND_TO_RESOURCE.get(kind)

    def kind_map(self) -> dict[str, str]:
        from kubernetes_tpu.api.meta import KIND_TO_RESOURCE
        merged = dict(KIND_TO_RESOURCE)
        merged.update(self._disc_kinds or {})
        return merged

    # -- URL helpers -------------------------------------------------------

    def _collection_url(self, resource: str, namespace: str | None) -> str:
        if self.is_cluster_scoped(resource) or not namespace:
            return f"{self.base_url}/api/v1/{resource}"
        return f"{self.base_url}/api/v1/namespaces/{namespace}/{resource}"

    def _item_url(self, resource: str, key: str) -> str:
        if "/" in key:
            ns, name = key.split("/", 1)
            return (f"{self.base_url}/api/v1/namespaces/{ns}/"
                    f"{resource}/{name}")
        return f"{self.base_url}/api/v1/{resource}/{key}"

    async def _json(self, resp: aiohttp.ClientResponse):
        if resp.content_type == PROTOBUF_CT:
            # runtime.Unknown envelope (see apiserver/grpc_server._wrap).
            from kubernetes_tpu.apiserver.grpc_server import (
                _unwrap,
                ktpu_pb2,
            )
            raw = await resp.read()
            if resp.status < 400:
                return _unwrap(ktpu_pb2.Unknown.FromString(raw))
            body = raw.decode(errors="replace")
            _raise_for_status(resp.status, body)
        try:
            body = await resp.json()
        except (aiohttp.ContentTypeError, json.JSONDecodeError):
            body = await resp.text()
        _raise_for_status(resp.status, body)
        return body

    # -- CRUD --------------------------------------------------------------

    async def create(self, resource: str, obj: Mapping, **_kw) -> dict:
        ns = obj.get("metadata", {}).get("namespace")
        async with self._sess().post(
                self._collection_url(resource, ns), json=dict(obj),
                headers=self._trace_headers()) as resp:
            return await self._json(resp)

    async def get(self, resource: str, key: str) -> dict:
        async with self._sess().get(self._item_url(resource, key)) as resp:
            return await self._json(resp)

    async def update(self, resource: str, obj: Mapping, **_kw) -> dict:
        key = namespaced_name(obj)
        async with self._sess().put(
                self._item_url(resource, key), json=dict(obj),
                headers=self._trace_headers()) as resp:
            return await self._json(resp)

    async def dry_run(self, resource: str, obj: Mapping,
                      operation: str = "update") -> dict:
        """Server-side dry run (?dryRun=All — kubectl diff's seam): the
        object flows through the FULL admission chain — mutating
        webhooks, expression policies, validating webhooks — and the
        admitted result comes back WITHOUT being persisted (no RV
        assigned, no watch event)."""
        params = {"dryRun": "All"}
        if operation == "create":
            ns = obj.get("metadata", {}).get("namespace")
            async with self._sess().post(
                    self._collection_url(resource, ns), json=dict(obj),
                    params=params,
                    headers=self._trace_headers()) as resp:
                return await self._json(resp)
        key = namespaced_name(obj)
        async with self._sess().put(
                self._item_url(resource, key), json=dict(obj),
                params=params,
                headers=self._trace_headers()) as resp:
            return await self._json(resp)

    async def delete(self, resource: str, key: str, *,
                     uid: str | None = None) -> dict:
        kwargs = {}
        if uid:
            kwargs["json"] = {"preconditions": {"uid": uid}}
        async with self._sess().delete(
                self._item_url(resource, key),
                headers=self._trace_headers(), **kwargs) as resp:
            return await self._json(resp)

    async def guaranteed_update(
        self, resource: str, key: str,
        mutate: Callable[[dict], dict | None],
        max_retries: int = 16, return_copy: bool = True,
    ) -> dict | None:
        """Client-side CAS loop (util/retry.RetryOnConflict)."""
        from kubernetes_tpu.client.retry import retry_on_conflict
        return await retry_on_conflict(
            self, resource, key, mutate,
            max_retries=max_retries, return_copy=return_copy)

    async def subresource(self, resource: str, key: str, sub: str,
                          body: Mapping) -> dict:
        url = self._item_url(resource, key) + "/" + sub
        async with self._sess().post(
                url, json=dict(body),
                headers=self._trace_headers()) as resp:
            return await self._json(resp)

    async def patch(self, resource: str, key: str, patch: Mapping, *,
                    patch_type: str = "strategic") -> dict:
        """kubectl patch: strategic-merge (default), merge, or json patch
        — the server merges against the live object and the result flows
        through its full admission chain (webhooks + policies)."""
        ct = {
            "strategic": "application/strategic-merge-patch+json",
            "merge": "application/merge-patch+json",
            "json": "application/json-patch+json",
        }.get(patch_type)
        if ct is None:
            raise ValueError(f"unknown patch type {patch_type!r}")
        headers = {"Content-Type": ct, **(self._trace_headers() or {})}
        async with self._sess().patch(
                self._item_url(resource, key),
                data=json.dumps(patch), headers=headers) as resp:
            return await self._json(resp)

    async def apply(self, resource: str, obj: Mapping, *,
                    field_manager: str, force: bool = False) -> dict:
        """Server-side apply (PATCH application/apply-patch+yaml)."""
        key = namespaced_name(obj)
        params = {"fieldManager": field_manager}
        if force:
            params["force"] = "true"
        async with self._sess().patch(
                self._item_url(resource, key), params=params,
                data=json.dumps(dict(obj)),
                headers={"Content-Type":
                         "application/apply-patch+yaml"}) as resp:
            return await self._json(resp)

    # -- LIST + WATCH ------------------------------------------------------

    async def list(
        self, resource: str, namespace: str | None = None,
        selector: Selector | None = None, limit: int = 0,
        continue_key: str | None = None,
        fields: Mapping[str, str] | None = None,
        *,
        resource_version: int | None = None,
        resource_version_match: str | None = None,
        **_kw,
    ) -> ListResult:
        params = {}
        sel = selector_to_string(selector)
        if sel:
            params["labelSelector"] = sel
        fs = field_selector_to_string(fields)
        if fs:
            params["fieldSelector"] = fs
        if limit:
            params["limit"] = str(limit)
        if continue_key:
            params["continue"] = continue_key
        if resource_version:
            # Watch-cache RV semantics (store/cacher.py): Exact pins the
            # historical snapshot; bare RV = "not older than" = current.
            params["resourceVersion"] = str(resource_version)
            if resource_version_match:
                params["resourceVersionMatch"] = resource_version_match
        async with self._sess().get(
                self._collection_url(resource, namespace),
                params=params) as resp:
            body = await self._json(resp)
        return ListResult(
            items=body.get("items", []),
            resource_version=int(
                body.get("metadata", {}).get("resourceVersion", 0)),
            cont=body.get("metadata", {}).get("continue"))

    async def watch(
        self, resource: str, resource_version: int = 0,
        namespace: str | None = None, selector: Selector | None = None,
        fields: Mapping[str, str] | None = None,
        **_kw,
    ) -> AsyncIterator[Event]:
        params = {"watch": "1"}
        if resource_version:
            params["resourceVersion"] = str(resource_version)
        sel = selector_to_string(selector)
        if sel:
            params["labelSelector"] = sel
        fs = field_selector_to_string(fields)
        if fs:
            params["fieldSelector"] = fs
        resp = await self._sess().get(
            self._collection_url(resource, namespace), params=params,
            timeout=aiohttp.ClientTimeout(total=None, sock_read=None))
        if resp.status >= 400:
            try:
                body = await resp.json()
            except (aiohttp.ContentTypeError, json.JSONDecodeError):
                body = await resp.text()
            resp.release()
            _raise_for_status(resp.status, body)

        async def gen() -> AsyncIterator[Event]:
            try:
                async for raw in _stream_lines(resp):
                    line = raw.strip()
                    if not line:
                        continue
                    frame = json.loads(line)
                    obj = frame.get("object") or {}
                    rv = int(obj.get("metadata", {})
                             .get("resourceVersion", 0) or 0)
                    if frame.get("type") == "ERROR":
                        reason = obj.get("reason", "")
                        if reason in ("Expired", "Gone"):
                            raise Expired(obj.get("message", "watch expired"))
                        raise StoreError(obj.get("message", "watch error"))
                    yield Event(frame["type"], obj, rv)
            except (aiohttp.ClientError, ValueError) as e:
                # Transport hiccups / oversized frames become a retriable
                # StoreError so the informer relists instead of dying.
                raise StoreError(f"watch stream error: {e}") from e
            finally:
                resp.release()

        return gen()


_MAX_FRAME = 64 << 20  # hard stop against a newline-free (corrupt) stream


async def _stream_lines(resp: aiohttp.ClientResponse):
    """Newline-split the watch stream from raw chunks, so a single frame
    larger than the reader's line limit can't kill the watch."""
    buf = bytearray()
    async for chunk in resp.content.iter_any():
        buf.extend(chunk)
        if len(buf) > _MAX_FRAME:
            raise ValueError(f"watch frame exceeded {_MAX_FRAME} bytes")
        while True:
            nl = buf.find(b"\n")
            if nl < 0:
                break
            line = bytes(buf[:nl])
            del buf[:nl + 1]
            yield line
    if buf:
        yield bytes(buf)
