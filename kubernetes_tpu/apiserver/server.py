"""REST+JSON API server over MVCCStore.

Parity targets:
- staging/src/k8s.io/apiserver `pkg/server/config.go DefaultBuildHandlerChain`
  → the aiohttp middleware stack (recovery → request-info → authn →
  priority-and-fairness → audit), in the reference's order.
- `pkg/endpoints/handlers/{create,get,watch,rest}.go` → the resource routes.
- `pkg/util/flowcontrol` (APF) → `PriorityLevel` fair-queued seats with
  SHUFFLE SHARDING (see `PriorityLevel` below): each flow (User-Agent) is
  dealt a deterministic hand of candidate queues and enqueues on the
  shortest; queues drain round-robin into a bounded seat pool, 429 +
  Retry-After on queue overflow.
- `pkg/registry/core/pod/storage/storage.go BindingREST.Create` → the
  pods/binding subresource route.
- watch wire: newline-delimited JSON WatchEvents with BOOKMARK frames and
  `410 Gone` on expired resourceVersions (`pkg/storage/cacher`).

Paths accept both core (`/api/v1/...`) and group (`/apis/<g>/<v>/...`)
prefixes; resources map 1:1 onto store tables.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from collections import deque
from typing import Mapping

from aiohttp import web

from kubernetes_tpu.api.labels import parse_field_selector, parse_selector
from kubernetes_tpu.metrics.registry import APIServerMetrics
from kubernetes_tpu.utils.tracing import stamp_traceparent
from kubernetes_tpu.store.mvcc import (
    AlreadyExists,
    Conflict,
    Expired,
    Invalid,
    MVCCStore,
    NotFound,
    StoreError,
)

logger = logging.getLogger(__name__)



PROTOBUF_CT = "application/vnd.kubernetes.protobuf"


def _wants_protobuf(request: web.Request) -> bool:
    return PROTOBUF_CT in request.headers.get("Accept", "")


def _object_response(request: web.Request, obj: dict,
                     status: int = 200) -> web.Response:
    """Content negotiation (§5.8: core components speak protobuf over
    HTTP): a client accepting application/vnd.kubernetes.protobuf gets
    the runtime.Unknown envelope (TypeMeta + raw JSON payload bytes —
    the same wire the gRPC service carries); everyone else gets JSON."""
    if _wants_protobuf(request):
        from kubernetes_tpu.apiserver.grpc_server import _wrap
        return web.Response(status=status,
                            body=_wrap(obj).SerializeToString(),
                            content_type=PROTOBUF_CT)
    return web.json_response(obj, status=status)


def _status_body(code: int, reason: str, message: str) -> dict:
    return {"kind": "Status", "apiVersion": "v1", "status": "Failure",
            "reason": reason, "code": code, "message": message}


def _code_reason(exc: Exception) -> tuple[int, str]:
    if isinstance(exc, NotFound):
        return 404, "NotFound"
    if isinstance(exc, AlreadyExists):
        return 409, "AlreadyExists"
    if isinstance(exc, Conflict):
        return 409, "Conflict"
    if isinstance(exc, Invalid):
        return 422, "Invalid"
    if isinstance(exc, Expired):
        return 410, "Expired"
    if isinstance(exc, web.HTTPException):
        return exc.status, type(exc).__name__
    if isinstance(exc, (ValueError, json.JSONDecodeError)):
        return 400, "BadRequest"
    return 500, "InternalError"


def _error_response(exc: StoreError) -> web.Response:
    code, reason = _code_reason(exc)
    return web.json_response(_status_body(code, reason, str(exc)), status=code)


class PriorityLevel:
    """APF fair queuing with shuffle sharding (pkg/util/flowcontrol).

    `seats` concurrent requests execute. Excess requests park in one of
    `num_queues` FIFO queues: a flow's identity deals it a HAND of
    `hand_size` candidate queues (deterministic shuffle shard, the
    reference's dealer) and the request joins the shortest — an elephant
    flow fills at most its hand while mice flows' hands almost surely
    include an uncontended queue. Seats drain queues round-robin (the
    reference's virtual-finish-time fair queue, order-approximated).
    A request arriving to a full shortest-queue gets 429 + Retry-After —
    reject-when-queue-full.
    """

    def __init__(self, name: str, seats: int = 16, queue_limit: int = 128,
                 num_queues: int = 64, hand_size: int = 8):
        self.name = name
        self.seats = seats
        #: per-queue length limit (the reference's queueLengthLimit).
        self.queue_limit = queue_limit
        self.num_queues = max(1, num_queues)
        self.hand_size = max(1, min(hand_size, self.num_queues))
        self._in_use = 0
        self._queues: list[deque] = [deque() for _ in range(self.num_queues)]
        #: round-robin dispatch cursor over queues.
        self._rr_next = 0
        self._waiting = 0

    @property
    def in_use(self) -> int:
        return self._in_use

    @property
    def queued(self) -> int:
        return self._waiting

    def _hand(self, flow: str) -> list[int]:
        """Deterministic shuffle shard: deal `hand_size` DISTINCT queue
        indices from the flow's hash (shufflesharding.Dealer)."""
        import hashlib
        h = int.from_bytes(hashlib.blake2b(
            f"{self.name}/{flow}".encode(), digest_size=8).digest(), "big")
        hand = []
        remaining = self.num_queues
        for _ in range(self.hand_size):
            h, pick = divmod(h, remaining)
            # map pick over the indices not yet dealt
            for taken in sorted(hand):
                if pick >= taken:
                    pick += 1
            hand.append(pick)
            remaining -= 1
        return hand

    async def acquire(self, flow: str) -> None:
        if self._in_use < self.seats and self._waiting == 0:
            self._in_use += 1
            return
        hand = self._hand(flow)
        qi = min(hand, key=lambda i: len(self._queues[i]))
        q = self._queues[qi]
        if len(q) >= self.queue_limit:
            raise web.HTTPTooManyRequests(
                headers={"Retry-After": "1"},
                text=json.dumps(_status_body(
                    429, "TooManyRequests",
                    f"priority level {self.name!r} queue full")),
                content_type="application/json")
        fut = asyncio.get_event_loop().create_future()
        q.append(fut)
        self._waiting += 1
        try:
            await fut
        except asyncio.CancelledError:
            if fut.done() and not fut.cancelled():
                # release() handed us the seat in the same tick the task
                # was cancelled — give it back or it leaks forever.
                self.release()
            else:
                try:
                    q.remove(fut)
                    self._waiting -= 1
                except ValueError:
                    pass
            raise
        # seat was transferred to us by release()

    def release(self) -> None:
        if self._waiting == 0:  # uncontended hot path: skip the scan
            self._in_use -= 1
            return
        # Hand the seat to the next waiter, round-robin across queues.
        for _ in range(self.num_queues):
            qi = self._rr_next
            self._rr_next = (self._rr_next + 1) % self.num_queues
            q = self._queues[qi]
            while q:
                fut = q.popleft()
                self._waiting -= 1
                if not fut.done():
                    fut.set_result(None)
                    return  # seat transferred
                # waiter was cancelled; try the next in this queue
        self._in_use -= 1


class APIServer:
    """Serve an MVCCStore over HTTP. One instance per "cluster"."""

    def __init__(self, store: MVCCStore | None = None, *,
                 host: str = "127.0.0.1", port: int = 0,
                 priority_levels: Mapping[str, PriorityLevel] | None = None,
                 bearer_tokens: Mapping[str, str] | None = None,
                 token_authenticator=None,
                 user_groups: Mapping[str, list[str]] | None = None,
                 authorizer=None,
                 admission=None,
                 metrics_registry=None,
                 audit_log: bool = False,
                 audit=None,
                 tracer=None,
                 data_dir: str | None = None,
                 fsync: str | None = None):
        #: Durability bootstrap (SURVEY §5.4, reachable END TO END — not
        #: just from tests): `data_dir` (or KTPU_DATA_DIR when no store
        #: is injected) recovers the newest snapshot + WAL tail on
        #: construction and runs the background flusher/snapshotter for
        #: the server's lifetime (started in start(), final snapshot in
        #: stop()). Passing a store AND a data_dir attaches the WAL to
        #: that store without recovery (the caller owns its contents).
        self.durability = None
        if store is None:
            from kubernetes_tpu.utils import flags
            data_dir = data_dir or flags.get("KTPU_DATA_DIR")
            if not data_dir:
                raise ValueError(
                    "APIServer needs a store, a data_dir, or KTPU_DATA_DIR")
        #: remembered so a stop()/start() cycle of the same instance
        #: re-attaches a fresh WAL instead of silently running without
        #: durability (stop closes the log file and detaches the sink).
        self._data_dir = data_dir
        self._fsync = fsync
        if data_dir:
            from kubernetes_tpu.store import (
                install_core_validation,
                new_cluster_store,
                recover_store,
            )
            if store is None:
                store = recover_store(data_dir, factory=new_cluster_store)
                install_core_validation(store)
        self.store = store
        self.host = host
        self.port = port
        #: route key → level. "system" catches lease/event traffic so node
        #: heartbeats survive workload floods (the APF design goal).
        self.priority_levels = dict(priority_levels or {
            "system": PriorityLevel("system", seats=64),
            "workload": PriorityLevel("workload", seats=32),
        })
        self.bearer_tokens = dict(bearer_tokens or {})  # token -> username
        #: dynamic authenticator (ServiceAccountAuthenticator): token ->
        #: username | None, consulted after the static map.
        self.token_authenticator = token_authenticator
        #: username -> group names, the authn side of Group subjects; the
        #: implicit system:authenticated/unauthenticated groups are added
        #: per-request (reference: authenticatorfactory + user.Info.Groups).
        self.user_groups = {u: list(g) for u, g in
                            (user_groups or {}).items()}
        #: RBACAuthorizer (apiserver/rbac.py) or None = authz disabled
        #: (the reference's AlwaysAllow mode).
        self.authorizer = authorizer
        #: WebhookAdmission (apiserver/admission.py) or None = no
        #: mutating/validating webhook out-calls.
        self.admission = admission
        self.metrics_registry = metrics_registry
        #: policy/audit.AuditPipeline or None = no stage-event audit
        #: (the legacy `audit_log` flat line remains available).
        self.audit = audit
        #: apiserver_request_duration_seconds / current_inflight — one
        #: instance shared with the KTPU wire (for_apiserver), so
        #: /metrics shows the whole request load across both wires.
        self.request_metrics = APIServerMetrics()
        if metrics_registry is not None:
            # Watch-dispatch counters live on the store (it owns dispatch);
            # surface them through this server's /metrics exposition.
            store.watch_metrics.register_into(metrics_registry)
            if store.cacher is not None:
                # Watch-cache serving-tier counters (hits/misses/ring).
                store.cacher.metrics.register_into(metrics_registry)
            self.request_metrics.register_into(metrics_registry)
            if audit is not None:
                audit.register_into(metrics_registry)
            engine = getattr(admission, "policy_engine", None)
            if engine is not None:
                engine.register_into(metrics_registry)
        self.audit_log = audit_log
        #: OTel-style request spans (SURVEY §5.1); defaults to the
        #: process tracer, which is disabled unless someone enables it.
        if tracer is None:
            from kubernetes_tpu.utils.tracing import DEFAULT_TRACER
            tracer = DEFAULT_TRACER
        self.tracer = tracer
        self._runner: web.AppRunner | None = None
        self._proxy_session = None  # shared aggregator proxy client
        self.app = self._build_app()

    # -- handler chain (DefaultBuildHandlerChain order) --------------------

    def _build_app(self) -> web.Application:
        # The reference's DefaultBuildHandlerChain order (§3.2): authn →
        # audit → impersonation → APF → authz. Audit sits OUTSIDE
        # impersonation so RequestReceived carries the authenticated
        # principal and ResponseComplete records the impersonated one;
        # authz runs innermost, as the impersonated user.
        app = web.Application(middlewares=[
            self._mw_recovery,        # WithPanicRecovery
            self._mw_request_info,    # WithRequestInfo
            self._mw_request_metrics,  # request duration + inflight (§5.5)
            self._mw_trace,           # WithTracing (OTel spans, §5.1)
            self._mw_authn,           # WithAuthentication
            self._mw_audit,           # WithAudit (stage events, §5.5)
            self._mw_impersonation,   # WithImpersonation
            self._mw_priority,        # WithPriorityAndFairness
            self._mw_authz,           # WithAuthorization (RBAC, innermost)
        ])
        app.router.add_get("/healthz", self._healthz)
        app.router.add_get("/readyz", self._healthz)
        app.router.add_get("/metrics", self._metrics)
        # Discovery + OpenAPI (kubectl's first requests).
        app.router.add_get("/api", self._discovery_core)
        app.router.add_get("/apis", self._discovery_groups)
        app.router.add_get("/api/{version}", self._resource_list)
        app.router.add_get("/apis/{group}/{version}", self._resource_list)
        app.router.add_get("/openapi/v2", self._openapi)
        for prefix in ("/api/{version}", "/apis/{group}/{version}"):
            # Namespaced routes first: "/api/v1/namespaces/ns/pods" must not
            # be captured by the generic "{resource}/{name}/{subresource}".
            app.router.add_route(
                "*", prefix + "/namespaces/{namespace}/{resource}",
                self._collection)
            app.router.add_route(
                "*", prefix + "/namespaces/{namespace}/{resource}/{name}",
                self._item)
            app.router.add_route(
                "*",
                prefix + "/namespaces/{namespace}/{resource}/{name}/{subresource}",
                self._sub)
            app.router.add_route(
                "*", prefix + "/{resource}", self._collection)
            app.router.add_route(
                "*", prefix + "/{resource}/{name}", self._item)
            app.router.add_route(
                "*", prefix + "/{resource}/{name}/{subresource}", self._sub)
        return app

    @web.middleware
    async def _mw_recovery(self, request: web.Request, handler):
        try:
            return await handler(request)
        except web.HTTPException:
            raise
        except StoreError as e:
            return _error_response(e)
        except asyncio.CancelledError:
            raise
        except (ValueError, json.JSONDecodeError) as e:
            # Malformed client input (bad selector/limit/body JSON) is the
            # client's fault: 400, not 500 (the reference's BadRequest).
            return web.json_response(
                _status_body(400, "BadRequest", str(e)), status=400)
        except Exception:
            logger.exception("panic in handler for %s", request.path)
            return web.json_response(
                _status_body(500, "InternalError", "internal error"),
                status=500)

    @web.middleware
    async def _mw_request_info(self, request: web.Request, handler):
        m = request.match_info
        request["resource"] = m.get("resource", "")
        request["namespace"] = m.get("namespace")
        request["verb"] = {
            "GET": "watch" if request.query.get("watch") else (
                "get" if m.get("name") else "list"),
            "POST": "create", "PUT": "update", "DELETE": "delete",
            "PATCH": "patch",
        }.get(request.method, request.method.lower())
        return await handler(request)

    @web.middleware
    async def _mw_request_metrics(self, request: web.Request, handler):
        """apiserver_request_duration_seconds{verb,resource,code} +
        apiserver_current_inflight_requests{request_kind}. Non-resource
        paths (health, metrics, discovery) and long-running requests
        (watches) are excluded from BOTH families — a watch's "duration"
        is its stream lifetime, which would poison the latency
        percentiles (and differ from the KTPU wire's registration-time
        view of the same verb)."""
        m = self.request_metrics
        verb = request["verb"]
        resource = request.get("resource", "")
        if m is None or not resource or verb == "watch":
            return await handler(request)
        m.inc_inflight(verb)
        t0 = time.perf_counter()
        try:
            resp = await handler(request)
        except Exception as e:
            m.observe(verb, resource, _code_reason(e)[0],
                      time.perf_counter() - t0)
            raise
        finally:
            m.dec_inflight(verb)
        m.observe(verb, resource, resp.status, time.perf_counter() - t0)
        return resp

    @web.middleware
    async def _mw_trace(self, request: web.Request, handler):
        t = self.tracer
        if t is None or not t.enabled:
            return await handler(request)
        attrs = {"client": request.headers.get("User-Agent", "")}
        if request["resource"] == "pods" and request.match_info.get("name"):
            ns = request["namespace"] or "default"
            attrs["pod"] = f"{ns}/{request.match_info['name']}"
        with t.span(
                f"apiserver.{request['verb']}.{request['resource'] or 'misc'}",
                traceparent=request.headers.get("traceparent"),
                **attrs) as sp:
            try:
                resp = await handler(request)
            except Exception as e:
                # _mw_recovery (outside this span) will map the
                # exception; record the status HERE or every failed
                # request's span reads like a success in Perfetto.
                sp.attrs["status"] = _code_reason(e)[0]
                raise
            sp.attrs["status"] = resp.status
            return resp

    @web.middleware
    async def _mw_authn(self, request: web.Request, handler):
        user = "system:anonymous"
        auth = request.headers.get("Authorization", "")
        if auth.startswith("Bearer "):
            token = auth[len("Bearer "):]
            user = self.bearer_tokens.get(token)
            if user is None and self.token_authenticator is not None:
                user = self.token_authenticator(token)
            if user is None:
                if self.bearer_tokens or \
                        self.token_authenticator is not None:
                    return web.json_response(
                        _status_body(401, "Unauthorized", "invalid token"),
                        status=401)
                user = "system:anonymous"
        request["user"] = user
        self.tracer.annotate(user=user)  # identity, not client library
        return await handler(request)

    def _groups_for(self, user: str) -> list[str]:
        """Configured groups + the implicit authn group — the same set for
        local authz and the aggregator's X-Remote-Group, so group bindings
        behave identically on both sides of the proxy."""
        groups = list(self.user_groups.get(user, ()))
        groups.append("system:unauthenticated"
                      if user == "system:anonymous"
                      else "system:authenticated")
        return groups

    def _request_groups(self, request: web.Request) -> list[str]:
        """Effective groups for the request's CURRENT identity —
        Impersonate-Group headers win over configured group membership
        once impersonation swapped users (the reference's
        user.Info.Groups after the impersonation filter)."""
        override = request.get("groups")
        if override is not None:
            return override
        return self._groups_for(request.get("user", "system:anonymous"))

    @web.middleware
    async def _mw_impersonation(self, request: web.Request, handler):
        """WithImpersonation: Impersonate-User swaps the request identity
        when RBAC grants the AUTHENTICATED user the `impersonate` verb on
        `users` (plugin order: after audit — so audit sees both sides —
        before APF/authz, which run as the impersonated user)."""
        target = request.headers.get("Impersonate-User")
        if not target:
            return await handler(request)
        user = request.get("user", "system:anonymous")
        if self.authorizer is not None and not self.authorizer.allowed(
                user, "impersonate", "users",
                groups=self._groups_for(user)):
            return web.json_response(_status_body(
                403, "Forbidden",
                f'user "{user}" cannot impersonate user "{target}"'),
                status=403)
        imp_groups = request.headers.getall("Impersonate-Group", [])
        if imp_groups and self.authorizer is not None and \
                not self.authorizer.allowed(
                    user, "impersonate", "groups",
                    groups=self._groups_for(user)):
            # Group impersonation is a SEPARATE grant (the reference
            # checks each impersonated attribute on its own resource):
            # impersonate-on-users must not let a caller self-assign
            # arbitrary group memberships.
            return web.json_response(_status_body(
                403, "Forbidden",
                f'user "{user}" cannot impersonate groups'), status=403)
        request["original_user"] = user
        request["impersonated_user"] = target
        request["user"] = target
        if imp_groups:
            request["groups"] = [*imp_groups, "system:authenticated"]
        self.tracer.annotate(user=target)
        return await handler(request)

    @web.middleware
    async def _mw_authz(self, request: web.Request, handler):
        # Non-resource paths (health, metrics, discovery, openapi) are
        # exempt — the reference grants them via system:discovery
        # nonResourceURLs; RBAC rules here are verb × resource only.
        if self.authorizer is None or not request.get("resource"):
            return await handler(request)
        user = request.get("user", "system:anonymous")
        verb = request.get("verb", "")
        resource = request.get("resource", "")
        if not self.authorizer.allowed(user, verb, resource,
                                       groups=self._request_groups(request)):
            return web.json_response(_status_body(
                403, "Forbidden",
                f'user "{user}" cannot {verb} resource "{resource}"'),
                status=403)
        return await handler(request)

    def _classify(self, request: web.Request) -> PriorityLevel:
        """Flow-schema-lite: leases + events + node status ride the system
        level; everything else is workload."""
        if request["resource"] in ("leases", "events"):
            return self.priority_levels["system"]
        return self.priority_levels["workload"]

    @web.middleware
    async def _mw_priority(self, request: web.Request, handler):
        if request.path in ("/healthz", "/readyz", "/metrics"):
            return await handler(request)
        if request["verb"] == "watch":
            return await handler(request)  # watches hold no seat (cacher)
        level = self._classify(request)
        flow = request.headers.get("User-Agent", "unknown")
        await level.acquire(flow)
        try:
            return await handler(request)
        finally:
            level.release()

    @web.middleware
    async def _mw_audit(self, request: web.Request, handler):
        """WithAudit: policy-selected level, RequestReceived emitted
        before the inner chain (pre-impersonation identity — audit sits
        outside the impersonation filter, like the reference), and
        ResponseComplete after, carrying the final status plus
        `impersonatedUser` when the identity was swapped mid-chain."""
        pipeline = self.audit
        resource = request.get("resource", "")
        if pipeline is None or not resource:
            resp = await handler(request)
            if self.audit_log:
                logger.info(
                    "audit user=%s verb=%s resource=%s ns=%s name=%s "
                    "code=%s",
                    request.get("user"), request.get("verb"),
                    request.get("resource"), request.get("namespace"),
                    request.match_info.get("name"), resp.status)
            return resp
        from kubernetes_tpu.policy.audit import (  # noqa: PLC0415 — lazy:
            LEVEL_REQUEST,                         # policy/ is optional
            LEVEL_REQUEST_RESPONSE,                # for audit-less servers
            level_at_least,
        )
        user = request.get("user", "system:anonymous")
        groups = self._groups_for(user)
        verb = request.get("verb", "")
        namespace = request.get("namespace")
        rule = pipeline.policy.rule_for(
            user=user, groups=groups, verb=verb, resource=resource,
            namespace=namespace)
        level = rule.get("level", "None") if rule else "None"
        req_obj = None
        if level_at_least(level, LEVEL_REQUEST) and request.can_read_body:
            # aiohttp caches the raw body, so the handler's own
            # request.json() still works after this read.
            try:
                req_obj = json.loads(await request.read())
            except (ValueError, json.JSONDecodeError):
                req_obj = None
        name = request.match_info.get("name") or \
            ((req_obj or {}).get("metadata") or {}).get("name")
        ctx = pipeline.begin(
            user=user, groups=groups, verb=verb, resource=resource,
            namespace=namespace, name=name, request_object=req_obj,
            rule=rule)
        try:
            resp = await handler(request)
        except Exception as e:
            pipeline.response_complete(
                ctx, code=_code_reason(e)[0],
                impersonated_user=request.get("impersonated_user"))
            raise
        resp_obj = None
        # Creates carry no name in the URL: the reference fills
        # objectRef.Name from the RESPONSE object at ResponseComplete.
        # Only creates — a LIST also has no URL name, but parsing a
        # multi-MB list body to hunt for a name it cannot contain would
        # tax the serving path for nothing.
        need_name = ctx is not None and verb == "create" and \
            not ctx["objectRef"]["name"]
        if (need_name
                or level_at_least(level, LEVEL_REQUEST_RESPONSE)) and \
                getattr(resp, "body", None) and \
                "json" in (resp.content_type or ""):
            try:
                parsed = json.loads(resp.body)
            except (ValueError, json.JSONDecodeError, TypeError):
                parsed = None
            if need_name and isinstance(parsed, dict):
                ctx["objectRef"]["name"] = (
                    parsed.get("metadata") or {}).get("name", "")
            if level_at_least(level, LEVEL_REQUEST_RESPONSE):
                resp_obj = parsed
        pipeline.response_complete(
            ctx, code=resp.status, response_object=resp_obj,
            impersonated_user=request.get("impersonated_user"))
        if self.audit_log:
            logger.info(
                "audit user=%s verb=%s resource=%s ns=%s name=%s code=%s",
                user, verb, resource, namespace,
                request.match_info.get("name"), resp.status)
        return resp

    # -- endpoints ---------------------------------------------------------

    async def _healthz(self, request: web.Request) -> web.Response:
        return web.Response(text="ok")

    # -- discovery + OpenAPI (kubectl bootstrap; kube-aggregator shape) ----

    async def _discovery_core(self, request: web.Request) -> web.Response:
        return web.json_response({"kind": "APIVersions", "versions": ["v1"]})

    async def _discovery_groups(self, request: web.Request) -> web.Response:
        """APIGroupList: built-in groups plus aggregated APIServices."""
        groups = {"apps", "batch", "storage.k8s.io", "scheduling.x-k8s.io",
                  "topology.node.k8s.io", "autoscaling", "policy",
                  "rbac.authorization.k8s.io", "apiextensions.k8s.io"}
        for svc in self.store._table("apiservices").values():
            g = (svc.get("spec") or {}).get("group")
            if g:
                groups.add(g)
        return web.json_response({
            "kind": "APIGroupList",
            "groups": [{"name": g, "versions": [{"version": "v1"}]}
                       for g in sorted(groups)]})

    async def _resource_list(self, request: web.Request) -> web.Response:
        """APIResourceList — kubectl's kind↔resource mapping request
        (GET /apis/apps/v1 etc.). Serves the full known set per group
        version; aggregated groups proxy."""
        proxied = await self._maybe_proxy(request)
        if proxied is not None:
            return proxied
        gv = request.match_info.get("version", "v1")
        group = request.match_info.get("group", "")
        return web.json_response({
            "kind": "APIResourceList",
            "groupVersion": f"{group}/{gv}" if group else gv,
            "resources": [
                {"name": resource, "kind": kind,
                 "namespaced": not self.store.is_cluster_scoped(resource),
                 "verbs": ["get", "list", "watch", "create", "update",
                           "delete"]}
                for kind, resource in sorted(self.store.kind_map().items())],
        })

    async def _openapi(self, request: web.Request) -> web.Response:
        """Minimal swagger 2.0: one path pair per known resource."""
        paths = {}
        for kind, resource in sorted(self.store.kind_map().items()):
            base = f"/api/v1/{resource}" if self.store.is_cluster_scoped(
                resource) else f"/api/v1/namespaces/{{namespace}}/{resource}"
            paths[base] = {"get": {"operationId": f"list{kind}"},
                           "post": {"operationId": f"create{kind}"}}
            paths[base + "/{name}"] = {
                "get": {"operationId": f"read{kind}"},
                "put": {"operationId": f"replace{kind}"},
                "delete": {"operationId": f"delete{kind}"}}
        return web.json_response({
            "swagger": "2.0",
            "info": {"title": "kubernetes-tpu", "version": "v1"},
            "paths": paths})

    def _aggregated_target(self, group: str) -> str | None:
        """kube-aggregator handler_proxy: an APIService object with
        spec.group == <group> routes the whole /apis/<group>/... subtree
        to its extension server."""
        for svc in self.store._table("apiservices").values():
            spec = svc.get("spec") or {}
            if spec.get("group") == group and \
                    (spec.get("service") or {}).get("url"):
                return spec["service"]["url"].rstrip("/")
        return None

    # Client credentials are stripped, not forwarded: the reference
    # aggregator authenticates ITSELF to extension servers and passes the
    # caller's identity via X-Remote-* headers (kube-aggregator
    # handler_proxy + x509 requestheader authn). Forwarding the bearer
    # token would hand every client's credential to whoever registers an
    # APIService.
    _HOP_HEADERS = {"host", "connection", "keep-alive", "transfer-encoding",
                    "upgrade", "proxy-authorization", "te", "trailers",
                    "authorization", "cookie"}

    @classmethod
    def _forwardable(cls, header: str) -> bool:
        h = header.lower()
        # Every client-supplied x-remote-* is dropped (not just user/group):
        # the extension trusts that namespace as proxy-asserted identity, so
        # forwarding e.g. X-Remote-Extra-Scopes would let callers inject
        # attributes onto their verified identity.
        return h not in cls._HOP_HEADERS and not h.startswith("x-remote-")

    def _proxy_client(self):
        import aiohttp
        if self._proxy_session is None:
            # Bounded total timeout: a blackholed extension server must not
            # pin APF workload seats for aiohttp's 5-minute default (the
            # WebhookAdmission session pattern). Watches override per-call.
            self._proxy_session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=30.0))
        return self._proxy_session

    async def _maybe_proxy(self,
                           request: web.Request) -> web.StreamResponse | None:
        group = request.match_info.get("group")
        if not group:
            return None
        target = self._aggregated_target(group)
        if target is None:
            return None
        import aiohttp
        url = target + request.path_qs
        body = await request.read() if request.can_read_body else None
        headers = {k: v for k, v in request.headers.items()
                   if self._forwardable(k)}
        ruser = request.get("user", "system:anonymous")
        headers["X-Remote-User"] = ruser
        rgroups = self._groups_for(ruser)
        headers["X-Remote-Group"] = ",".join(rgroups)
        is_watch = bool(request.query.get("watch"))
        resp = None
        try:
            session = self._proxy_client()
            kwargs = {}
            if is_watch:
                # Long-lived stream: no total deadline, just connect.
                kwargs["timeout"] = aiohttp.ClientTimeout(
                    total=None, sock_connect=5.0)
            async with session.request(request.method, url, data=body,
                                       headers=headers, **kwargs) as r:
                if is_watch:
                    # Stream the chunked watch frames through.
                    resp = web.StreamResponse(status=r.status)
                    resp.content_type = r.content_type
                    await resp.prepare(request)
                    async for chunk in r.content.iter_any():
                        await resp.write(chunk)
                    await resp.write_eof()
                    return resp
                return web.Response(
                    status=r.status, body=await r.read(),
                    content_type=r.content_type or "application/json")
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            # TimeoutError is what the total ClientTimeout raises — it is
            # NOT a ClientError subclass.
            if resp is not None and resp.prepared:
                # Headers already sent (extension died mid-watch): end the
                # stream cleanly; a second response body would corrupt the
                # connection.
                try:
                    await resp.write_eof()
                except (ConnectionError, RuntimeError):
                    pass
                return resp
            return web.json_response(_status_body(
                503, "ServiceUnavailable",
                f"aggregated apiserver for {group!r} unreachable: {e}"),
                status=503)

    async def _metrics(self, request: web.Request) -> web.Response:
        text = ""
        if self.metrics_registry is not None:
            text = self.metrics_registry.render()
        return web.Response(text=text, content_type="text/plain")

    @staticmethod
    def _key(request: web.Request) -> str:
        ns, name = request["namespace"], request.match_info["name"]
        return f"{ns}/{name}" if ns else name

    async def _collection(self, request: web.Request) -> web.StreamResponse:
        proxied = await self._maybe_proxy(request)
        if proxied is not None:
            return proxied
        resource = request["resource"]
        if request.method == "GET":
            if request.query.get("watch"):
                return await self._watch(request)
            sel = None
            if request.query.get("labelSelector"):
                sel = parse_selector(request.query["labelSelector"])
            fields = None
            if request.query.get("fieldSelector"):
                fields = parse_field_selector(
                    request.query["fieldSelector"])
            limit = int(request.query.get("limit", 0) or 0)
            cont = request.query.get("continue")
            # RV-semantics params (the cacher contract, store/cacher.py):
            # resourceVersion + resourceVersionMatch=Exact serves the
            # historical snapshot; bare/0 RVs serve "any cached" =
            # current. Continue tokens carry their own RV pin.
            rv_q = request.query.get("resourceVersion")
            rv = int(rv_q) if rv_q and rv_q.isdigit() and int(rv_q) \
                else None
            lst = await self.store.list(
                resource, namespace=request["namespace"], selector=sel,
                limit=limit, continue_key=cont, fields=fields,
                resource_version=rv,
                resource_version_match=request.query.get(
                    "resourceVersionMatch"),
                copy=False)  # encode-only: serialized before return
            body = {
                "kind": "List", "apiVersion": "v1",
                "metadata": {"resourceVersion": str(lst.resource_version)},
                "items": lst.items,
            }
            if lst.cont:
                # Snapshot-pinned token off the cacher: later pages are
                # served at THIS page's RV (identical on the KTPU wire).
                body["metadata"]["continue"] = lst.cont
            elif limit and len(lst.items) >= limit:
                # Legacy (cacher disabled): the bare store key of the
                # last item (store.list resumes strictly after it).
                last = lst.items[-1]["metadata"]
                ns = last.get("namespace")
                body["metadata"]["continue"] = \
                    f"{ns}/{last['name']}" if ns else last["name"]
            return web.json_response(body)
        if request.method == "POST":
            obj = await request.json()
            if request["namespace"] and not obj.get(
                    "metadata", {}).get("namespace"):
                obj.setdefault("metadata", {})["namespace"] = \
                    request["namespace"]
            if resource == "pods":
                meta = obj.get("metadata") or {}
                ns = meta.get("namespace") or "default"
                self.tracer.annotate(pod=f"{ns}/{meta.get('name', '')}")
                # Carry this request's trace across the informer/queue
                # boundary: the scheduler parents its attempt span to the
                # stamped traceparent (no-op with tracing off).
                stamp_traceparent(obj)
            if self.admission is not None:
                with self.tracer.span("admission.webhooks",
                                      resource=resource, op="create"):
                    obj = await self.admission.admit(
                        obj, resource, "create",
                        user=request.get("user"),
                        groups=self._request_groups(request))
            if request.query.get("dryRun"):
                # dryRun=All (kubectl diff's seam): the FULL admission
                # chain ran above, and the store's mutators+validators
                # run here too (defaulting becomes VISIBLE in the
                # diff; an unpersistable object fails the dry run the
                # way a real create would). Only uniqueness/RV checks
                # are skipped — nothing persists, no watch event.
                admit = getattr(self.store, "_admit", None)
                if admit is not None:
                    admit(resource, obj, "create")
                return _object_response(request, obj, status=201)
            with self.tracer.span("store.create", resource=resource):
                created = await self.store.create(resource, obj)
            return _object_response(request, created, status=201)
        raise web.HTTPMethodNotAllowed(request.method, ["GET", "POST"])

    async def _item(self, request: web.Request) -> web.Response:
        proxied = await self._maybe_proxy(request)
        if proxied is not None:
            return proxied
        resource, key = request["resource"], self._key(request)
        if request.method == "GET":
            return _object_response(
                request, await self.store.get(resource, key))
        if request.method == "PUT":
            obj = await request.json()
            # The URL fully identifies the object; default the body's
            # metadata from it so a sparse body can't target the wrong key.
            meta = obj.setdefault("metadata", {})
            meta.setdefault("name", request.match_info["name"])
            if request["namespace"]:
                meta.setdefault("namespace", request["namespace"])
            if self.admission is not None:
                obj = await self.admission.admit(
                    obj, resource, "update", user=request.get("user"),
                    groups=self._request_groups(request))
            if request.query.get("dryRun"):
                # Admission + store mutators/validators ran; the
                # update is NOT persisted (see the POST dryRun note).
                admit = getattr(self.store, "_admit", None)
                if admit is not None:
                    admit(resource, obj, "update")
                return _object_response(request, obj)
            return _object_response(
                request, await self.store.update(resource, obj))
        if request.method == "PATCH" and "apply-patch" in \
                request.headers.get("Content-Type", ""):
            # Server-side apply (application/apply-patch+yaml): the
            # fieldManager param names the owner; force transfers
            # conflicting fields (SURVEY §2.7).
            obj = await request.json()
            meta = obj.setdefault("metadata", {})
            meta.setdefault("name", request.match_info["name"])
            if request["namespace"]:
                meta.setdefault("namespace", request["namespace"])
            manager = request.query.get("fieldManager", "")
            if not manager:
                return web.json_response(_status_body(
                    400, "BadRequest", "fieldManager is required"),
                    status=400)
            if self.admission is not None:
                obj = await self.admission.admit(
                    obj, resource, "update", user=request.get("user"),
                    groups=self._request_groups(request))
            out = await self.store.apply(
                resource, obj, field_manager=manager,
                force=request.query.get("force") in ("true", "1"))
            # 200 for both create and update (the reference 201s fresh
            # creates; callers here key off the object, not the code).
            return _object_response(request, out)
        if request.method == "PATCH":
            # Strategic-merge / merge patch (kubectl patch): read-modify-
            # write over the live object. The merged result flows through
            # the FULL admission chain — webhooks + expression policies —
            # exactly like a PUT (the reference's patchResource path).
            ct = request.headers.get("Content-Type", "")
            patch = await request.json()
            from kubernetes_tpu.store.apply import strategic_merge_patch
            # Patch carries no client RV precondition, so a concurrent
            # writer must not surface as a spurious 409: re-read and
            # re-merge on Conflict (the reference's patchResource retry).
            for attempt in range(8):
                current = await self.store.get(resource, key)
                if "json-patch" in ct:
                    from kubernetes_tpu.apiserver.admission import (
                        apply_json_patch,
                    )
                    merged = apply_json_patch(current, patch)
                else:
                    # application/strategic-merge-patch+json and
                    # application/merge-patch+json: dict deep-merge; the
                    # strategic variant also merges named list entries.
                    merged = strategic_merge_patch(
                        current, patch, strategic="strategic" in ct or
                        not ct.startswith("application/merge-patch"))
                if self.admission is not None:
                    merged = await self.admission.admit(
                        merged, resource, "update",
                        user=request.get("user"),
                        groups=self._request_groups(request))
                try:
                    return _object_response(
                        request, await self.store.update(resource, merged))
                except Conflict:
                    if attempt == 7:
                        raise
                    continue
        if request.method == "DELETE":
            uid = None
            if request.can_read_body:
                try:
                    body = await request.json()
                    uid = (body.get("preconditions") or {}).get("uid")
                except (ValueError, json.JSONDecodeError):
                    pass
            if self.admission is not None:
                # Webhooks see the object being deleted (patches have no
                # meaning on delete; deny aborts it).
                current = await self.store.get(resource, key)
                await self.admission.admit(
                    current, resource, "delete",
                    user=request.get("user"),
                    groups=self._request_groups(request))
            return web.json_response(
                await self.store.delete(resource, key, uid=uid))
        raise web.HTTPMethodNotAllowed(
            request.method, ["GET", "PUT", "PATCH", "DELETE"])

    async def _sub(self, request: web.Request) -> web.Response:
        proxied = await self._maybe_proxy(request)
        if proxied is not None:
            return proxied
        resource, key = request["resource"], self._key(request)
        sub = request.match_info["subresource"]
        if sub == "status" and request.method == "PUT":
            obj = await request.json()
            # The key comes from the URL; the subresource only replaces
            # `.status` over the live object (the reference's StatusREST).
            # A resourceVersion in the body is an optimistic-concurrency
            # precondition: mismatch → 409, as with a full-object PUT.
            status = obj.get("status", {})
            want_rv = obj.get("metadata", {}).get("resourceVersion")

            def merge_status(current: dict) -> dict:
                if want_rv and \
                        str(current["metadata"]["resourceVersion"]) != str(want_rv):
                    raise Conflict(
                        f"{resource} {key!r}: resourceVersion mismatch")
                current["status"] = status
                return current

            out = await self.store.guaranteed_update(
                resource, key, merge_status)
            return web.json_response(out)
        if request.method != "POST":
            raise web.HTTPMethodNotAllowed(request.method, ["POST"])
        body = await request.json()
        with self.tracer.span(f"store.subresource.{sub}",
                              resource=resource):
            result = await self.store.subresource(resource, key, sub, body)
        return web.json_response(result, status=201)

    async def _watch(self, request: web.Request) -> web.StreamResponse:
        """Chunked newline-delimited WatchEvents (the reference's
        `Transfer-Encoding: chunked` watch stream)."""
        resource = request["resource"]
        rv = int(request.query.get("resourceVersion", 0) or 0)
        sel = None
        if request.query.get("labelSelector"):
            sel = parse_selector(request.query["labelSelector"])
        fields = None
        if request.query.get("fieldSelector"):
            # The kubelet's watch shape (spec.nodeName=<me>): exact-match
            # field terms ride the store's tracked-field index, so this
            # wire's fan-out is O(matching watchers) too.
            fields = parse_field_selector(request.query["fieldSelector"])
        try:
            watch = await self.store.watch(
                resource, resource_version=rv,
                namespace=request["namespace"], selector=sel,
                fields=fields)
        except Expired as e:
            return _error_response(e)
        resp = web.StreamResponse(
            status=200,
            headers={"Content-Type": "application/json;stream=watch"})
        await resp.prepare(request)
        from kubernetes_tpu.apiserver.wire import encode_event_object
        try:
            async for ev in watch:
                if ev.type == "BOOKMARK":
                    frame = (b'{"type":"BOOKMARK","object":{"metadata":'
                             b'{"resourceVersion":"' + str(ev.rv).encode()
                             + b'"}}}\n')
                else:
                    # Spliced frame: object bytes encoded once per event
                    # ACROSS its synthesized twins too (encode_event_object
                    # follows _wire_src — SURVEY §3.2). The splice itself
                    # stays per-connection: memoizing the whole frame would
                    # pin a second full copy of every object on events
                    # retained in the 200k-entry history window.
                    frame = (b'{"type":"' + ev.type.encode()
                             + b'","object":' + encode_event_object(ev)
                             + b'}\n')
                await resp.write(frame)
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            aclose = getattr(watch, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:
                    pass
        return resp

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self.durability is None and self._data_dir:
            from kubernetes_tpu.store import DurabilityManager
            self.durability = DurabilityManager(
                self.store, self._data_dir, fsync=self._fsync)
        if self.durability is not None:
            self.durability.start()
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        # Resolve the ephemeral port.
        server = site._server  # noqa: SLF001
        if server and server.sockets:
            self.port = server.sockets[0].getsockname()[1]
        logger.info("apiserver listening on %s:%d", self.host, self.port)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._proxy_session is not None:
            await self._proxy_session.close()
            self._proxy_session = None
        if self.audit is not None:
            await self.audit.close()
        if self.admission is not None:
            await self.admission.close()
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        if self.durability is not None:
            # Final snapshot: a clean shutdown leaves one compact
            # snapshot file, so the next boot replays no WAL tail.
            await self.durability.stop(final_snapshot=True)
            self.durability = None
