"""KTPU wire: multiplexed framed transport for core components.

Parity target: the reference's core components speak protobuf over
HTTP/2 to the apiserver — ONE long-lived connection per component,
many concurrent requests multiplexed as streams (client-go transport
uses http2.Transport; watches are server-push streams on the same
connection). Python has no usable HTTP/2 server in-tree, and per-request
HTTP/1.1 costs ~230µs/req on one core — so this module implements the
multiplexing idea directly: length-prefixed frames over one TCP
connection, request ids instead of streams, watch events pushed as
frames on the same socket. Same wire role, ~13× the throughput of the
aiohttp path on this host (59k vs 4.4k msg/s microbench).

Server semantics mirror the HTTP handler chain in `server.py`
(DefaultBuildHandlerChain order): recovery → authn (handshake) →
priority-and-fairness seats → audit → RBAC authz → admission webhooks →
store. A WireServer shares the APIServer's PriorityLevels, tokens,
authorizer and admission objects, so policy is identical on both wires.

Frame format: 4-byte big-endian length + body. The body is msgpack (the
protobuf-role binary codec: ~3x faster to encode/decode than JSON on
this host and ~25% smaller on the socket) or JSON — codecs are
self-distinguishing (msgpack arrays start 0x9x/0xdc/0xdd, JSON arrays
with '['), so each side decodes per frame and replies in the codec the
peer last spoke. Core components use msgpack; JSON remains for
debugging and hand-rolled clients.
  client→server: [id, op, ...args]
    ["", "hello", {"token": t, "ua": ...}]     (id "" = pre-auth)
    [id, "create", resource, obj]
    [id, "get", resource, key]
    [id, "update", resource, obj]
    [id, "delete", resource, key, uid|null]
    [id, "sub", resource, key, subresource, body]
    [id, "list", resource, {namespace, selector, limit, continue}]
    [id, "watch", resource, {rv, namespace, selector}]   (id = watch id)
    [id, "stopwatch"]
    [id, "kinds"]                               (discovery: kind map)
    [id, "multi", [[op, ...args], ...]]         (same-tick op batch)
  server→client: [id, "ok", result] | [id, "err", reason, message]
    [watch_id, "ev", TYPE, object]              (watch push)
    [watch_id, "exp", message]                  (watch 410/terminated)

Reference pointers (SURVEY §5.8 comms backend, §3.2 watch fan-out):
staging/src/k8s.io/apimachinery/pkg/watch, client-go transport/cache.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import struct
import time
from typing import AsyncIterator, Callable, Mapping

import msgpack

from kubernetes_tpu.utils.locking import check_dispatch_seam

from kubernetes_tpu.api.labels import (
    Selector,
    parse_selector,
    selector_to_string,
)
from kubernetes_tpu.store.mvcc import (
    AlreadyExists,
    Conflict,
    Event,
    Expired,
    Invalid,
    ListResult,
    MVCCStore,
    NotFound,
    StoreError,
)
from kubernetes_tpu.utils.tracing import stamp_traceparent

logger = logging.getLogger(__name__)

_NULL_CM = contextlib.nullcontext()

_LEN = struct.Struct(">I")
_MAX_FRAME = 64 << 20

_REASON_OF = {
    NotFound: "NotFound",
    AlreadyExists: "AlreadyExists",
    Conflict: "Conflict",
    Invalid: "Invalid",
    Expired: "Expired",
}
_EXC_OF = {v: k for k, v in _REASON_OF.items()}

_VERB_OF = {"create": "create", "get": "get", "update": "update",
            "delete": "delete", "sub": "update", "list": "list",
            "watch": "watch", "kinds": "get", "apply": "patch"}

#: StoreError reason → HTTP-equivalent code for audit responseStatus.
_CODE_OF_REASON = {"NotFound": 404, "AlreadyExists": 409,
                   "Conflict": 409, "Invalid": 422, "Expired": 410,
                   "Forbidden": 403, "TooManyRequests": 429,
                   "BadRequest": 400, "Unauthorized": 401}

_dumps = json.dumps
_packb = msgpack.packb
_unpackb = msgpack.unpackb


def _decode_frame(payload: bytes):
    """Decode one frame body, either codec. Returns (frame, is_msgpack)."""
    lead = payload[0]
    if lead == 0x5B or lead in (0x20, 0x09, 0x0A, 0x0D):  # '[' / ws → JSON
        return json.loads(payload), False
    return _unpackb(payload), True


def _encode_reply(frame: list, mp: bool) -> bytes:
    """Encode a server reply in the codec the peer speaks (the server-side
    dual of WireStore._encode)."""
    return _packb(frame) if mp else \
        _dumps(frame, separators=(",", ":")).encode()


def _reason_for(exc: StoreError) -> str:
    for cls, reason in _REASON_OF.items():
        if isinstance(exc, cls):
            return reason
    return "InternalError"


def _encode_memo(ev: Event, attr: str, encode) -> bytes:
    """Per-codec encode-once across an event AND its synthesized
    enter/leave twins: the store delivers the same Event instance to all
    channels of a selector group, and a twin links its source via
    `_wire_src` (store/mvcc.py `_synth`) — they share one object, so
    they share one encoding. The memo is read from/written to both ends
    of the link, so whichever watcher encodes first pays for everyone
    (SURVEY §3.2 — the reference cacher serializes once per event, not
    per watcher)."""
    b = getattr(ev, attr, None)
    if b is not None:
        return b
    src = getattr(ev, "_wire_src", None)
    if src is not None:
        b = getattr(src, attr, None)
    if b is None:
        b = encode(ev.object)
        if src is not None:
            try:
                setattr(src, attr, b)
            except AttributeError:
                pass
    try:
        setattr(ev, attr, b)
    except AttributeError:  # frozen/slots object: still correct, no memo
        pass
    return b


def encode_event_object(ev: Event) -> bytes:
    """JSON-encode a watch event's object once per event (+ twins),
    shared across every watcher on both wires."""
    return _encode_memo(
        ev, "_wire_obj",
        lambda obj: _dumps(obj, separators=(",", ":")).encode())


def encode_event_object_mp(ev: Event) -> bytes:
    """msgpack twin of encode_event_object — one packing per event
    shared across every msgpack watcher."""
    return _encode_memo(ev, "_wire_obj_mp", _packb)


class _Conn(asyncio.Protocol):
    """One client connection on the server side."""

    def __init__(self, server: "WireServer"):
        self.server = server
        self.transport: asyncio.Transport | None = None
        self.buf = bytearray()
        self.user = "system:anonymous"
        #: the AUTHENTICATED principal — differs from `user` when the
        #: hello frame's impersonate field swapped identities; audit
        #: events record this as `user` and `user` as impersonatedUser.
        self.auth_user = "system:anonymous"
        self.flow = "wire"
        #: codec the peer speaks (learned per received frame; replies and
        #: watch pushes mirror it).
        self._mp = False
        #: one hello per connection (see _hello).
        self._hello_done = False
        #: watch id -> pump task
        self.watches: dict[str, asyncio.Task] = {}
        self._out: list[bytes] = []
        self._flush_scheduled = False
        self._closed = False
        #: transport backpressure (pause_writing/resume_writing): watch
        #: pumps await this so a slow consumer parks its pumps instead of
        #: growing the transport buffer without bound.
        self._drained = asyncio.Event()
        self._drained.set()

    def pause_writing(self) -> None:
        self._drained.clear()

    def resume_writing(self) -> None:
        self._drained.set()

    # -- transport ---------------------------------------------------------

    def connection_made(self, transport: asyncio.Transport) -> None:
        self.transport = transport
        transport.set_write_buffer_limits(high=8 << 20)
        self.server._conns.add(self)

    def connection_lost(self, exc) -> None:
        self._closed = True
        for t in self.watches.values():
            t.cancel()
        self.watches.clear()
        self.server._conns.discard(self)

    def data_received(self, data: bytes) -> None:
        # Offset-scan then ONE tail compaction: a coalesced read can hold
        # hundreds of frames, and `del buf[:4+n]` per frame is an O(bytes)
        # memmove each time — quadratic over the burst.
        buf = self.buf
        buf.extend(data)
        end = len(buf)
        ofs = 0
        while end - ofs >= 4:
            n = _LEN.unpack_from(buf, ofs)[0]
            if n > _MAX_FRAME:
                logger.error("wire: oversized frame (%d bytes); closing", n)
                self.transport.close()
                return
            if end - ofs - 4 < n:
                break
            payload = bytes(buf[ofs + 4:ofs + 4 + n])
            ofs += 4 + n
            try:
                frame, self._mp = _decode_frame(payload)
            except Exception:
                logger.error("wire: undecodable frame; closing")
                self.transport.close()
                return
            asyncio.ensure_future(self._handle(frame))
        if ofs:
            del buf[:ofs]

    # -- batched writes ----------------------------------------------------

    def send(self, body: bytes) -> None:
        if self._closed:
            return
        self._out.append(_LEN.pack(len(body)))
        self._out.append(body)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_event_loop().call_soon(self._flush)

    def _flush(self) -> None:
        self._flush_scheduled = False
        if self._out and not self._closed:
            # Sanctioned wire-send seam: the lock-hygiene detector
            # (KTPU_LOCK_CHECK=1) raises here if the flushing thread
            # still holds an instrumented lock.
            check_dispatch_seam("wire.flush")
            self.transport.write(b"".join(self._out))
            self._out.clear()

    def _ok(self, rid: str, result) -> None:
        self.send(_encode_reply([rid, "ok", result], self._mp))

    def _err(self, rid: str, reason: str, message: str) -> None:
        self.send(_encode_reply([rid, "err", reason, message], self._mp))

    @staticmethod
    def _unwrap_traced(frame: list) -> tuple[str | None, list]:
        """Frame-field traceparent (the wire analog of the HTTP
        `traceparent` header): [id, "traced", tp, op, ...args] unwraps to
        (tp, [id, op, ...args]); untraced frames pass through. A
        non-string tp is dropped, not propagated — it would otherwise
        crash span creation OUTSIDE the error-reply path and hang the
        client's future."""
        if len(frame) > 3 and frame[1] == "traced":
            tp = frame[2] if isinstance(frame[2], str) else None
            return tp, [frame[0], *frame[3:]]
        return None, frame

    def _span_cm(self, op: str, resource: str, tp: str | None):
        """Server-side span for one frame op (a no-op context when the
        tracer is off)."""
        tracer = self.server.tracer
        if tracer is None or not tracer.enabled or op in (
                "hello", "stopwatch"):
            return _NULL_CM
        name = "wire.multi" if op == "multi" else \
            f"wire.{_VERB_OF.get(op, op)}.{resource or 'misc'}"
        return tracer.span(name, traceparent=tp, client=self.flow,
                           user=self.user)

    def _finish(self, actx, code: int, verb: str, resource: str,
                t0: float, result=None) -> None:
        """ResponseComplete + the request-duration observation — one call
        per frame-op outcome, mirroring where the HTTP chain's audit
        middleware and metrics middleware both fire. Watches are excluded
        from the duration family on both wires: here the frame finishes
        at registration, on HTTP at stream end — two incompatible
        semantics that would share one metric."""
        self._audit_end(actx, code, result)
        m = self.server.request_metrics
        if m is not None and resource and verb != "watch":
            m.observe(verb, resource, code, time.perf_counter() - t0)

    # -- handler chain (server.py middleware order) ------------------------

    # -- audit stage events ------------------------------------------------

    def _audit_begin(self, op: str, verb: str, resource: str,
                     frame: list):
        """RequestReceived for one frame op — BEFORE APF/authz, the
        reference chain position (audit outside everything but authn)."""
        pipeline = self.server.audit
        if pipeline is None or not resource:
            return None
        name = namespace = None
        request_object = None
        arg = frame[3] if len(frame) > 3 else None
        if op in ("create", "update", "apply") and isinstance(arg, dict):
            meta = arg.get("metadata") or {}
            name = meta.get("name")
            namespace = meta.get("namespace")
            request_object = arg
        elif isinstance(arg, str):  # get/delete/sub carry a key
            namespace, _, name = arg.rpartition("/")
            namespace = namespace or None
        return pipeline.begin(
            user=self.auth_user,
            groups=self.server.groups_for(self.auth_user),
            verb=verb, resource=resource, namespace=namespace,
            name=name, request_object=request_object)

    def _audit_end(self, actx, code: int, result=None) -> None:
        if actx is None:
            return
        self.server.audit.response_complete(
            actx, code=code,
            response_object=result if isinstance(result, dict) else None,
            impersonated_user=self.user
            if self.user != self.auth_user else None)

    async def _handle(self, frame: list) -> None:
        try:
            tp, frame = self._unwrap_traced(frame)
            op = frame[1]
            resource = frame[2] if len(frame) > 2 and \
                isinstance(frame[2], str) else ""
        except Exception:
            tp, op, resource = None, "", ""
        with self._span_cm(op, resource, tp):
            await self._handle_frame(frame)

    async def _handle_frame(self, frame: list) -> None:
        rid = ""
        actx = None
        verb = resource = ""
        t0 = time.perf_counter()
        try:
            rid, op = frame[0], frame[1]
            if op == "hello":
                return self._hello(rid, frame[2] or {})
            if op == "stopwatch":
                t = self.watches.pop(rid, None)
                if t is not None:
                    t.cancel()
                return
            if op == "multi":
                return await self._multi(rid, frame[2])
            srv = self.server
            verb = _VERB_OF.get(op, op)
            resource = frame[2] if len(frame) > 2 and \
                isinstance(frame[2], str) else ""
            # audit: RequestReceived before APF/authz (reference chain
            # position; authn + impersonation were hello-time).
            actx = self._audit_begin(op, verb, resource, frame)
            if op == "watch":
                # No APF seat (cacher semantics) but authz still applies.
                if srv.authorizer is not None and resource and \
                        not srv.authorizer.allowed(
                            self.user, verb, resource,
                            groups=srv.groups_for(self.user)):
                    self._finish(actx, 403, verb, resource, t0)
                    return self._err(
                        rid, "Forbidden",
                        f'user "{self.user}" cannot {verb} resource '
                        f'"{resource}"')
                await self._start_watch(rid, frame[2], frame[3] or {})
                self._finish(actx, 200, verb, resource, t0)
                return
            # APF: watches hold no seat (cacher semantics); everything
            # else acquires one from the shared priority levels.
            level = srv.classify(resource)
            if level is not None:
                try:
                    await level.acquire(self.flow)
                except Exception:
                    self._finish(actx, 429, verb, resource, t0)
                    return self._err(rid, "TooManyRequests",
                                     f"priority level {level.name!r} "
                                     "queue full")
            try:
                # authz (RBAC) innermost, as the (possibly impersonated)
                # request identity — same rule set as the HTTP server.
                if srv.authorizer is not None and resource and \
                        not srv.authorizer.allowed(
                            self.user, verb, resource,
                            groups=srv.groups_for(self.user)):
                    self._finish(actx, 403, verb, resource, t0)
                    return self._err(
                        rid, "Forbidden",
                        f'user "{self.user}" cannot {verb} resource '
                        f'"{resource}"')
                m = srv.request_metrics
                if m is not None:
                    m.inc_inflight(verb)
                try:
                    result = await self._dispatch(op, frame)
                finally:
                    if m is not None:
                        m.dec_inflight(verb)
            finally:
                if level is not None:
                    level.release()
            self._finish(actx, 200 if op != "create" else 201,
                         verb, resource, t0, result)
            self._ok(rid, result)
        except StoreError as e:
            reason = _reason_for(e)
            self._finish(actx, _CODE_OF_REASON.get(reason, 500),
                         verb, resource, t0)
            self._err(rid, reason, str(e))
        except asyncio.CancelledError:
            raise
        except (ValueError, KeyError, IndexError, TypeError) as e:
            self._finish(actx, 400, verb, resource, t0)
            self._err(rid, "BadRequest", f"malformed frame: {e!r}")
        except Exception:
            logger.exception("wire: panic handling frame")
            self._finish(actx, 500, verb, resource, t0)
            self._err(rid, "InternalError", "internal error")

    async def _multi(self, rid: str, ops: list) -> None:
        """Same-tick op batch from one client (the HTTP/2 concurrent-
        streams analog): runs sequentially under ONE APF seat — the batch
        is one scheduling unit of server work, like one connection's
        stream window. Per-op authz still applies; results are positional
        ["ok", result] | ["err", reason, message] pairs."""
        srv = self.server
        results: list = [None] * len(ops)
        # Per-member traceparents (the traced wrapper applies to multi
        # members too — each member is one request, so each gets its own
        # server span parented to its caller's span).
        member_tps: list[str | None] = [None] * len(ops)
        unwrapped: list = []
        for i, sub in enumerate(ops):
            if len(sub) > 2 and sub[0] == "traced":
                if isinstance(sub[1], str):  # see _unwrap_traced
                    member_tps[i] = sub[1]
                sub = list(sub[2:])
            unwrapped.append(sub)
        ops = unwrapped
        # Seats are held PER PRIORITY LEVEL, matching the single-op path:
        # a lease renewal coalesced into the same tick as a pod burst must
        # still ride the "system" level, or a full workload queue would
        # starve leader election — the exact failure APF exists to stop.
        by_level: dict[str | None, list[int]] = {}
        for idx, sub in enumerate(ops):
            resource = sub[1] if len(sub) > 1 and \
                isinstance(sub[1], str) else ""
            level = srv.classify(resource) if srv.priority_levels else None
            by_level.setdefault(
                level.name if level is not None else None,
                []).append(idx)
        for level_name, idxs in by_level.items():
            level = srv.priority_levels.get(level_name) \
                if level_name is not None else None
            if level is not None:
                try:
                    await level.acquire(self.flow)
                except Exception:
                    for idx in idxs:
                        results[idx] = ["err", "TooManyRequests",
                                        f"priority level {level.name!r} "
                                        "queue full"]
                    continue
            try:
                for idx in idxs:
                    sub = ops[idx]
                    op = sub[0]
                    actx = None
                    verb = resource = ""
                    t0 = time.perf_counter()
                    try:
                        resource = sub[1] if len(sub) > 1 and \
                            isinstance(sub[1], str) else ""
                        verb = _VERB_OF.get(op, op)
                        with self._span_cm(op, resource, member_tps[idx]):
                            # Per-op audit, same stages as the single-op
                            # path (one coalesced frame is N requests).
                            actx = self._audit_begin(op, verb, resource,
                                                     ["", *sub])
                            if srv.authorizer is not None and resource \
                                    and not srv.authorizer.allowed(
                                        self.user, verb, resource,
                                        groups=srv.groups_for(self.user)):
                                self._finish(actx, 403, verb, resource, t0)
                                results[idx] = [
                                    "err", "Forbidden",
                                    f'user "{self.user}" cannot {verb} '
                                    f'resource "{resource}"']
                                continue
                            m = srv.request_metrics
                            if m is not None:
                                m.inc_inflight(verb)
                            try:
                                result = await self._dispatch(
                                    op, ["", *sub])
                            finally:
                                if m is not None:
                                    m.dec_inflight(verb)
                            self._finish(
                                actx, 200 if op != "create" else 201,
                                verb, resource, t0, result)
                            results[idx] = ["ok", result]
                    except StoreError as e:
                        reason = _reason_for(e)
                        self._finish(actx, _CODE_OF_REASON.get(reason, 500),
                                     verb, resource, t0)
                        results[idx] = ["err", reason, str(e)]
                    except (ValueError, KeyError, IndexError,
                            TypeError) as e:
                        self._finish(actx, 400, verb, resource, t0)
                        results[idx] = ["err", "BadRequest",
                                        f"malformed op: {e!r}"]
            finally:
                if level is not None:
                    level.release()
        self._ok(rid, results)

    def _hello(self, rid: str, args: Mapping) -> None:
        srv = self.server
        if self._hello_done:
            # One handshake per connection: a second hello could reset
            # auth_user to the impersonated identity (erasing the real
            # principal from the audit trail) or re-authenticate the
            # session mid-stream. Refuse and drop the connection.
            self._err(rid, "BadRequest", "session already authenticated")
            self._flush()
            if self.transport is not None:
                self.transport.close()
            return
        self._hello_done = True
        token = args.get("token")
        self.flow = args.get("ua") or "wire"
        if token:
            user = srv.bearer_tokens.get(token)
            if user is None and srv.token_authenticator is not None:
                user = srv.token_authenticator(token)
            if user is None and (srv.bearer_tokens
                                 or srv.token_authenticator is not None):
                self._err(rid, "Unauthorized", "invalid token")
                # The HTTP chain 401s EVERY request carrying a bad token;
                # the connection-oriented analog is to refuse the session
                # outright — leaving it open would let the client keep
                # operating as system:anonymous.
                self._flush()
                if self.transport is not None:
                    self.transport.close()
                return
            self.user = user or "system:anonymous"
        self.auth_user = self.user
        target = args.get("impersonate")
        if target:
            # WithImpersonation, frame-field form: the session adopts the
            # target identity for every subsequent frame (client-go's
            # transport-level ImpersonationConfig), gated by the RBAC
            # `impersonate` verb for the AUTHENTICATED user. A denial
            # refuses the session, like a bad token — silently continuing
            # as the original user would mask a policy violation.
            if srv.authorizer is not None and not srv.authorizer.allowed(
                    self.auth_user, "impersonate", "users",
                    groups=srv.groups_for(self.auth_user)):
                self._err(rid, "Forbidden",
                          f'user "{self.auth_user}" cannot impersonate '
                          f'user "{target}"')
                self._flush()
                if self.transport is not None:
                    self.transport.close()
                return
            self.user = target
        self._ok(rid, {"user": self.user})

    async def _dispatch(self, op: str, frame: list):
        store = self.server.store
        admission = self.server.admission
        user = self.user
        groups = self.server.groups_for(user) \
            if admission is not None else None
        if op == "create":
            resource, obj = frame[2], frame[3]
            if resource == "pods":
                # Carry this frame's trace across the informer/queue
                # boundary (see utils/tracing.stamp_traceparent); no-op
                # outside a span.
                stamp_traceparent(obj)
            if admission is not None:
                obj = await admission.admit(obj, resource, "create",
                                            user=user, groups=groups)
            # The decoded object is exclusively ours (just parsed off the
            # socket): hand ownership to the store and skip its entry
            # deep-copy; the response encodes the stored object directly.
            created = await store.create(resource, obj, _owned=True)
            return created
        if op == "get":
            return await store.get(frame[2], frame[3])
        if op == "update":
            resource, obj = frame[2], frame[3]
            if admission is not None:
                obj = await admission.admit(obj, resource, "update",
                                            user=user, groups=groups)
            return await store.update(resource, obj)
        if op == "delete":
            resource, key = frame[2], frame[3]
            uid = frame[4] if len(frame) > 4 else None
            if admission is not None:
                current = await store.get(resource, key)
                await admission.admit(current, resource, "delete",
                                      user=user, groups=groups)
            return await store.delete(resource, key, uid=uid)
        if op == "sub":
            return await store.subresource(
                frame[2], frame[3], frame[4], frame[5])
        if op == "apply":
            resource, obj = frame[2], frame[3]
            if admission is not None:
                obj = await admission.admit(obj, resource, "update",
                                            user=user, groups=groups)
            return await store.apply(
                resource, obj, field_manager=frame[4],
                force=bool(frame[5] if len(frame) > 5 else False))
        if op == "list":
            resource, args = frame[2], frame[3] or {}
            sel = parse_selector(args["selector"]) \
                if args.get("selector") else None
            # RV semantics + snapshot-pinned continue tokens ride the
            # watch-cache tier (store/cacher.py) — same contract as the
            # HTTP wire's resourceVersion/resourceVersionMatch params,
            # so paginated pages agree on one snapshot RV across wires.
            kw = {}
            if args.get("shard") is not None \
                    and hasattr(store, "node_shards"):
                # Shard-scoped LIST (per-shard informer relists) —
                # ignored when the backing store is unsharded.
                kw["shard"] = int(args["shard"])
            lst = await store.list(
                resource, namespace=args.get("namespace"),
                selector=sel, limit=int(args.get("limit") or 0),
                continue_key=args.get("continue"),
                fields=args.get("fields") or None,
                resource_version=int(args.get("rv") or 0) or None,
                resource_version_match=args.get("rvMatch"),
                copy=False, **kw)  # encode-only: packed before return
            out = {"items": lst.items, "rv": lst.resource_version}
            if lst.cont:
                out["cont"] = lst.cont
            return out
        if op == "kinds":
            return {"kinds": store.kind_map(),
                    "clusterScoped": sorted(
                        r for r in set(store.kind_map().values())
                        if store.is_cluster_scoped(r))}
        if op == "topology":
            # Control-plane shape discovery: a sharded backing store
            # advertises its shard count + partitioned resources so
            # clients can open per-shard watches (ShardedInformer).
            return {"nodeShards": int(getattr(store, "node_shards", 1)),
                    "partitioned": list(
                        getattr(store, "partitioned_resources", ()))}
        if op == "stats":
            # Server-side observability snapshot: a shard process
            # reports its WAL/durability counters (and anything else the
            # host wired into stats_fn) so the parent can sum per-shard
            # deltas into the bench detail JSON without scraping
            # /metrics text.
            fn = getattr(self.server, "stats_fn", None)
            return dict(fn()) if fn is not None else {}
        raise ValueError(f"unknown op {op!r}")

    # -- watch push --------------------------------------------------------

    async def _start_watch(self, wid: str, resource: str,
                           args: Mapping) -> None:
        if wid in self.watches:
            return self._err(wid, "BadRequest", "watch id in use")
        sel = parse_selector(args["selector"]) \
            if args.get("selector") else None
        # Register the store channel HERE, inside the frame's own handler
        # task: frame handlers run in arrival order, so a write processed
        # after this watch frame is guaranteed to reach it. Spawning the
        # registration into the pump task would let an rv=0 ("from now")
        # watch miss writes that arrived just behind it.
        kw = {}
        if args.get("shard") is not None \
                and hasattr(self.server.store, "node_shards"):
            kw["shard"] = int(args["shard"])
        try:
            watch = await self.server.store.watch(
                resource, resource_version=int(args.get("rv") or 0),
                namespace=args.get("namespace"), selector=sel,
                fields=args.get("fields") or None, **kw)
        except Expired as e:
            self.send(_encode_reply([wid, "exp", str(e)], self._mp))
            return
        task = asyncio.ensure_future(self._watch_pump(wid, watch))
        self.watches[wid] = task
        task.add_done_callback(lambda _t: self.watches.pop(wid, None))

    async def _watch_pump(self, wid: str, watch) -> None:
        # Codec is fixed per connection by the time a watch starts (the
        # client spoke at least the hello + watch frames already).
        mp = self._mp
        wid_b = _packb(wid) if mp else _dumps(wid).encode()
        try:
            async for ev in watch:
                if ev.type == "BOOKMARK":
                    bm = {"metadata": {"resourceVersion": str(ev.rv)}}
                    body = (b"\x94" + wid_b + b"\xa2ev\xa8BOOKMARK"
                            + _packb(bm)) if mp else (
                        b'[' + wid_b + b',"ev","BOOKMARK",'
                        b'{"metadata":{"resourceVersion":"'
                        + str(ev.rv).encode() + b'"}}]')
                elif mp:
                    # Spliced msgpack frame [wid,"ev",TYPE,obj]: fixarray(4)
                    # header + concatenated elements — msgpack concatenates
                    # like JSON splices, and the object bytes are packed
                    # once per event across ALL watchers (the _mp memo).
                    body = (b"\x94" + wid_b + b"\xa2ev"
                            + _packb(ev.type) + encode_event_object_mp(ev))
                else:
                    # Spliced frame: the object bytes are encoded once per
                    # event across ALL watchers (encode_event_object memo).
                    body = (b'[' + wid_b + b',"ev","' + ev.type.encode()
                            + b'",' + encode_event_object(ev) + b']')
                self.send(body)
                if self._closed:
                    return
                if not self._drained.is_set():
                    # Slow consumer: park this pump until the transport
                    # drains (the HTTP path got this via `await write`).
                    # The store watch channel buffers meanwhile, bounded
                    # by its event window.
                    await self._drained.wait()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            logger.exception("wire: watch pump %s died", wid)
            self.send(_encode_reply([wid, "exp", f"watch error: {e}"], mp))
        finally:
            aclose = getattr(watch, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:
                    pass


class WireServer:
    """Serve an MVCCStore over the KTPU wire. Policy objects (priority
    levels, tokens, RBAC authorizer, admission, audit pipeline) are
    shared with an APIServer when one exists, so both wires enforce
    identical rules."""

    #: per-frame stage order, mirroring server.py's middleware list /
    #: the reference's DefaultBuildHandlerChain (§3.2). authn and
    #: impersonation are connection-scoped (the hello frame); the rest
    #: run per frame in this order — the chain-order tests pin it.
    HANDLER_CHAIN = ("authn", "audit", "impersonation", "apf", "authz",
                     "admission")

    def __init__(self, store: MVCCStore, *, host: str = "127.0.0.1",
                 port: int = 0, priority_levels: Mapping | None = None,
                 bearer_tokens: Mapping[str, str] | None = None,
                 token_authenticator=None,
                 user_groups: Mapping[str, list[str]] | None = None,
                 authorizer=None, admission=None, audit=None,
                 tracer=None, request_metrics=None):
        self.store = store
        self.host = host
        self.port = port
        self.priority_levels = dict(priority_levels or {})
        self.bearer_tokens = dict(bearer_tokens or {})
        self.token_authenticator = token_authenticator
        self.user_groups = {u: list(g) for u, g in
                            (user_groups or {}).items()}
        self.authorizer = authorizer
        self.admission = admission
        #: policy/audit.AuditPipeline or None (shared with the HTTP
        #: server via for_apiserver — ONE sink for both wires).
        self.audit = audit
        #: OTel-style per-frame spans (§5.1) — the frame-field analog of
        #: the HTTP wire's traceparent middleware.
        if tracer is None:
            from kubernetes_tpu.utils.tracing import DEFAULT_TRACER
            tracer = DEFAULT_TRACER
        self.tracer = tracer
        #: APIServerMetrics shared with the HTTP server (for_apiserver):
        #: both wires report into one request-duration family.
        self.request_metrics = request_metrics
        #: optional () -> dict for the `stats` op: a shard process wires
        #: its WAL/durability counters here so the parent can pull
        #: per-shard observability over the same socket.
        self.stats_fn = None
        self._server: asyncio.AbstractServer | None = None
        self._conns: set[_Conn] = set()
        self._path = ""

    @classmethod
    def for_apiserver(cls, api, *, host: str = "127.0.0.1",
                      port: int = 0) -> "WireServer":
        """Share the APIServer's policy objects (seats are one pool across
        both wires — a wire client and an HTTP client contend fairly)."""
        return cls(api.store, host=host, port=port,
                   priority_levels=api.priority_levels,
                   bearer_tokens=api.bearer_tokens,
                   token_authenticator=api.token_authenticator,
                   user_groups=api.user_groups,
                   authorizer=api.authorizer, admission=api.admission,
                   audit=api.audit, tracer=api.tracer,
                   request_metrics=api.request_metrics)

    def classify(self, resource: str):
        if not self.priority_levels:
            return None
        if resource in ("leases", "events"):
            return self.priority_levels.get("system") \
                or self.priority_levels.get("workload")
        return self.priority_levels.get("workload")

    def groups_for(self, user: str) -> list[str]:
        groups = list(self.user_groups.get(user, ()))
        groups.append("system:unauthenticated"
                      if user == "system:anonymous"
                      else "system:authenticated")
        return groups

    async def start(self) -> None:
        loop = asyncio.get_event_loop()
        if self.host.startswith("unix:"):
            # Unix-domain listener: same frames, ~30% less per-byte
            # syscall cost than TCP loopback — the co-located-component
            # fast path (the reference's apiserver on the same host).
            self._path = self.host[len("unix:"):] or \
                f"/tmp/ktpu-wire-{id(self):x}.sock"
            self._server = await loop.create_unix_server(
                lambda: _Conn(self), self._path)
            logger.info("wire server listening on unix:%s", self._path)
            return
        self._server = await loop.create_server(
            lambda: _Conn(self), self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("wire server listening on %s:%d", self.host, self.port)

    @property
    def target(self) -> str:
        if self.host.startswith("unix:"):
            return f"unix:{self._path}"
        return f"{self.host}:{self.port}"

    async def stop(self) -> None:
        for conn in list(self._conns):
            if conn.transport is not None:
                conn.transport.close()
        self._conns.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        if self._path:
            import os
            try:
                os.unlink(self._path)
            except OSError:
                pass
            self._path = ""


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class _ClientProto(asyncio.Protocol):
    def __init__(self, owner: "WireStore"):
        self.owner = owner
        self.buf = bytearray()
        self.transport: asyncio.Transport | None = None

    def connection_made(self, transport: asyncio.Transport) -> None:
        self.transport = transport
        transport.set_write_buffer_limits(high=8 << 20)

    def connection_lost(self, exc) -> None:
        self.owner._conn_lost(exc)

    def data_received(self, data: bytes) -> None:
        # Offset-scan + single compaction (see _Conn.data_received): the
        # server's watch-push bursts coalesce into large reads. The
        # compaction runs in `finally` so a decode/handler error cannot
        # leave already-delivered frames at the buffer head (they would
        # replay on the next read); an undecodable frame is fatal to the
        # connection, mirroring the server side.
        buf = self.buf
        buf.extend(data)
        end = len(buf)
        ofs = 0
        try:
            while end - ofs >= 4:
                n = _LEN.unpack_from(buf, ofs)[0]
                if end - ofs - 4 < n:
                    break
                payload = bytes(buf[ofs + 4:ofs + 4 + n])
                ofs += 4 + n
                try:
                    frame = _decode_frame(payload)[0]
                except Exception:
                    logger.error("wire client: undecodable frame; closing")
                    if self.transport is not None:
                        self.transport.close()
                    return
                self.owner._on_frame(frame)
        finally:
            if ofs:
                del buf[:ofs]


class _WireWatch:
    """Client side of one pushed watch stream.

    The queue is BOUNDED (advisor r4): the client reads the socket
    eagerly, so the server's pause_writing backpressure cannot protect a
    consumer that stops iterating — without a bound, events would pile
    up in this queue without limit. On overflow the watch terminates
    with the Expired signal, the same contract as the store channel's
    bounded window: the consumer relists and re-watches."""

    MAX_BUFFERED = 8192

    def __init__(self, wid: str):
        self.wid = wid
        self.queue: asyncio.Queue = asyncio.Queue()
        self.closed = False


class WireStore:
    """MVCCStore-shaped client over the KTPU wire — the core-component
    transport (informers, scheduler, controllers run over it unchanged).
    All ops multiplex over ONE connection; outgoing frames written in the
    same loop tick coalesce into one socket write."""

    def __init__(self, target: str, *, token: str | None = None,
                 user_agent: str = "kubernetes-tpu-wire",
                 enc: str = "msgpack", impersonate: str | None = None):
        if target.startswith("unix:"):
            self.path: str | None = target[len("unix:"):]
            self.host, self.port = "", 0
        else:
            self.path = None
            host, _, port = target.rpartition(":")
            self.host, self.port = host or "127.0.0.1", int(port)
        self.token = token
        self.user_agent = user_agent
        #: session-wide impersonation target (client-go's transport-level
        #: ImpersonationConfig analog) — rides the hello frame; the server
        #: RBAC-gates it on the authenticated user's `impersonate` verb.
        self.impersonate = impersonate
        #: frame codec: "msgpack" (default — the binary fast path) or
        #: "json"; the server mirrors whichever the client speaks.
        self._encode = (_packb if enc == "msgpack" else
                        lambda f: _dumps(f, separators=(",", ":")).encode())
        self._proto: _ClientProto | None = None
        self._next_id = 0
        self._pending: dict[str, asyncio.Future] = {}
        self._watches: dict[str, _WireWatch] = {}
        self._out: list[bytes] = []
        self._flush_scheduled = False
        #: ops issued in the current loop tick, coalesced into ONE `multi`
        #: frame at flush (the HTTP/2 concurrent-streams analog): a
        #: 128-wide asyncio.gather of creates becomes one frame + one
        #: server task instead of 128 of each.
        self._tick_ops: list[tuple[str, list]] = []
        #: multi frame id -> ordered member request ids
        self._multis: dict[str, list[str]] = {}
        self._connecting: asyncio.Future | None = None
        self._stopped = False
        self._kinds: dict[str, str] | None = None
        self._cluster_scoped: set[str] = set()

    # -- connection --------------------------------------------------------

    async def _ensure(self) -> None:
        if self._stopped:
            raise StoreError("wire store is closed")
        if self._proto is not None and self._proto.transport is not None \
                and not self._proto.transport.is_closing():
            return
        if self._connecting is not None:
            await self._connecting
            return
        loop = asyncio.get_event_loop()
        self._connecting = loop.create_future()
        try:
            if self.path is not None:
                _t, proto = await loop.create_unix_connection(
                    lambda: _ClientProto(self), self.path)
            else:
                _t, proto = await loop.create_connection(
                    lambda: _ClientProto(self), self.host, self.port)
            self._proto = proto
            hello_args = {"token": self.token, "ua": self.user_agent}
            if self.impersonate:
                hello_args["impersonate"] = self.impersonate
            hello = await self._call("hello", hello_args, _pre_auth=True)
            logger.debug("wire connected as %s", hello.get("user"))
            self._connecting.set_result(None)
        except BaseException as e:
            # A refused handshake must not leave a half-open session that
            # later calls would reuse unauthenticated. Transport-level
            # connect failures (refused/absent socket during a shard
            # restart window) surface as StoreError like every other
            # wire failure — one error surface for retry loops.
            if isinstance(e, OSError):
                e = StoreError(f"wire connect failed: {e}")
            if self._proto is not None and self._proto.transport is not None:
                self._proto.transport.close()
            self._proto = None
            fut, self._connecting = self._connecting, None
            fut.set_exception(e)
            fut.exception()  # retrieved: the creator raises below
            raise e
        self._connecting = None

    def _conn_lost(self, exc) -> None:
        err = StoreError(f"wire connection lost: {exc}")
        # Drop frames serialized but never written: their callers' futures
        # fail below, so replaying them on the next connection would
        # duplicate side effects (and run pre-hello as anonymous).
        self._out.clear()
        self._tick_ops.clear()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()
        for w in self._watches.values():
            w.closed = True
            w.queue.put_nowait(("exp", "wire connection lost"))
        self._watches.clear()
        self._multis.clear()
        self._proto = None

    async def close(self) -> None:
        self._stopped = True
        if self._proto is not None and self._proto.transport is not None:
            self._proto.transport.close()
        self._proto = None

    def stop(self) -> None:
        self._stopped = True
        if self._proto is not None and self._proto.transport is not None:
            self._proto.transport.close()
        self._proto = None

    # -- framing -----------------------------------------------------------

    def _send(self, frame: list) -> None:
        body = self._encode(frame)
        self._out.append(_LEN.pack(len(body)))
        self._out.append(body)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_event_loop().call_soon(self._flush)

    def _send_op(self, rid: str, op_frame: list) -> None:
        self._tick_ops.append((rid, op_frame))
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_event_loop().call_soon(self._flush)

    def _flush(self) -> None:
        self._flush_scheduled = False
        ops, self._tick_ops = self._tick_ops, []
        if len(ops) == 1:
            rid, op_frame = ops[0]
            body = self._encode([rid, *op_frame])
            self._out.append(_LEN.pack(len(body)))
            self._out.append(body)
        elif ops:
            self._next_id += 1
            mid = f"m{self._next_id}"
            self._multis[mid] = [rid for rid, _ in ops]
            body = self._encode([mid, "multi", [f for _, f in ops]])
            self._out.append(_LEN.pack(len(body)))
            self._out.append(body)
        if self._out and self._proto is not None \
                and self._proto.transport is not None:
            self._proto.transport.write(b"".join(self._out))
            self._out.clear()

    def _on_frame(self, frame: list) -> None:
        rid, kind = frame[0], frame[1]
        if kind == "ok" and rid in self._multis:
            for member_rid, res in zip(self._multis.pop(rid), frame[2]):
                fut = self._pending.pop(member_rid, None)
                if fut is None or fut.done():
                    continue
                if res[0] == "ok":
                    fut.set_result(res[1])
                else:
                    fut.set_exception(_EXC_OF.get(
                        res[1], StoreError)(res[2]))
            return
        if kind == "err" and rid in self._multis:
            exc = _EXC_OF.get(frame[2], StoreError)(frame[3])
            for member_rid in self._multis.pop(rid):
                fut = self._pending.pop(member_rid, None)
                if fut is not None and not fut.done():
                    fut.set_exception(exc)
            return
        if kind == "ev":
            w = self._watches.get(rid)
            if w is not None and not w.closed:
                if w.queue.qsize() >= w.MAX_BUFFERED:
                    # Consumer stopped draining: expire the watch instead
                    # of buffering without bound (see _WireWatch).
                    self._watches.pop(rid, None)
                    w.closed = True
                    w.queue.put_nowait(
                        ("exp", "watch expired: client buffer overflow "
                                "(consumer too slow)"))
                    self._send([rid, "stopwatch"])
                else:
                    w.queue.put_nowait(("ev", frame[2], frame[3]))
            return
        if kind == "exp":
            w = self._watches.pop(rid, None)
            if w is not None:
                w.closed = True
                w.queue.put_nowait(("exp", frame[2]))
            return
        fut = self._pending.pop(rid, None)
        if fut is None or fut.done():
            return
        if kind == "ok":
            fut.set_result(frame[2])
        else:  # err
            exc = _EXC_OF.get(frame[2], StoreError)
            fut.set_exception(exc(frame[3]))

    @staticmethod
    def _trace_wrap(op_frame: list) -> list:
        """W3C traceparent propagation, frame-field form: an op issued
        inside a span ships ["traced", tp, op, ...args] so the server's
        frame span parents to the caller's (the wire analog of
        RemoteStore's traceparent header)."""
        from kubernetes_tpu.utils.tracing import DEFAULT_TRACER
        if not DEFAULT_TRACER.enabled:
            return op_frame
        tp = DEFAULT_TRACER.current_traceparent()
        return ["traced", tp, *op_frame] if tp else op_frame

    async def _call(self, op: str, *args, _pre_auth: bool = False):
        if not _pre_auth:
            await self._ensure()
        self._next_id += 1
        rid = f"r{self._next_id}"
        fut = asyncio.get_event_loop().create_future()
        self._pending[rid] = fut
        if _pre_auth:
            self._send([rid, op, *args])  # hello must not ride a multi
        else:
            self._send_op(rid, self._trace_wrap([op, *args]))
        return await fut

    # -- MVCCStore surface -------------------------------------------------

    async def create(self, resource: str, obj: Mapping, **_kw) -> dict:
        return await self._call("create", resource, dict(obj))

    async def get(self, resource: str, key: str) -> dict:
        return await self._call("get", resource, key)

    async def update(self, resource: str, obj: Mapping, **_kw) -> dict:
        return await self._call("update", resource, dict(obj))

    async def delete(self, resource: str, key: str, *,
                     uid: str | None = None) -> dict:
        return await self._call("delete", resource, key, uid)

    async def subresource(self, resource: str, key: str, sub: str,
                          body: Mapping) -> dict:
        return await self._call("sub", resource, key, sub, dict(body))

    async def apply(self, resource: str, obj: Mapping, *,
                    field_manager: str, force: bool = False) -> dict:
        return await self._call("apply", resource, dict(obj),
                                field_manager, force)

    async def guaranteed_update(
        self, resource: str, key: str,
        mutate: Callable[[dict], dict | None],
        max_retries: int = 16, return_copy: bool = True,
    ) -> dict | None:
        """Client-side CAS loop (util/retry.RetryOnConflict)."""
        from kubernetes_tpu.client.retry import retry_on_conflict
        return await retry_on_conflict(
            self, resource, key, mutate,
            max_retries=max_retries, return_copy=return_copy)

    async def list(
        self, resource: str, namespace: str | None = None,
        selector: Selector | None = None, limit: int = 0,
        continue_key: str | None = None,
        fields: Mapping[str, str] | None = None,
        *,
        resource_version: int | None = None,
        resource_version_match: str | None = None,
        shard: int | None = None,
        **_kw,
    ) -> ListResult:
        args = {
            "namespace": namespace,
            "selector": selector_to_string(selector) or None,
            "limit": limit or 0, "continue": continue_key,
            "fields": dict(fields) if fields else None}
        if resource_version:
            args["rv"] = resource_version
            args["rvMatch"] = resource_version_match
        if shard is not None:
            args["shard"] = int(shard)
        resp = await self._call("list", resource, args)
        return ListResult(items=resp["items"],
                          resource_version=int(resp["rv"]),
                          cont=resp.get("cont"))

    async def watch(
        self, resource: str, resource_version: int = 0,
        namespace: str | None = None, selector: Selector | None = None,
        fields: Mapping[str, str] | None = None,
        shard: int | None = None,
        **_kw,
    ) -> AsyncIterator[Event]:
        await self._ensure()
        self._next_id += 1
        wid = f"w{self._next_id}"
        w = _WireWatch(wid)
        self._watches[wid] = w
        args = {
            "rv": resource_version or 0, "namespace": namespace,
            "selector": selector_to_string(selector) or None,
            "fields": dict(fields) if fields else None}
        if shard is not None:
            args["shard"] = int(shard)
        self._send([wid, "watch", resource, args])

        async def gen() -> AsyncIterator[Event]:
            try:
                while True:
                    kind, *rest = await w.queue.get()
                    if kind == "exp":
                        msg = rest[0]
                        if "too old" in msg or "expired" in msg.lower():
                            raise Expired(msg)
                        raise StoreError(msg)
                    ev_type, obj = rest
                    rv = int(obj.get("metadata", {})
                             .get("resourceVersion", 0) or 0)
                    yield Event(ev_type, obj, rv)
            finally:
                w.closed = True
                if self._watches.pop(wid, None) is not None \
                        and self._proto is not None:
                    self._send([wid, "stopwatch"])

        return gen()

    # -- discovery (RESTMapper analog, used by CLI-ish consumers) ----------

    async def control_topology(self) -> dict:
        """Server control-plane shape ({"nodeShards": S, "partitioned":
        [...]}), cached — ShardedInformer calls this once per informer
        start to decide between per-shard and single-stream reflectors.
        Servers predating the op report the unsharded shape."""
        if getattr(self, "_topology", None) is None:
            try:
                self._topology = await self._call("topology")
            except Exception:
                # Do NOT cache the failure: a transient error at probe
                # time must not pin this connection to the single-stream
                # path forever — the next informer start retries.
                logger.warning("topology probe failed; assuming an "
                               "unsharded server this time", exc_info=True)
                return {"nodeShards": 1, "partitioned": []}
        return self._topology

    async def control_stats(self) -> dict:
        """Server-side observability snapshot (the `stats` op): the
        shard process's WAL counters etc. Uncached — callers difference
        snapshots around a measured phase. Servers predating the op
        (or with no stats_fn wired) report {}."""
        try:
            return dict(await self._call("stats") or {})
        except Exception:
            logger.warning("stats probe failed; reporting empty",
                           exc_info=True)
            return {}

    async def refresh_discovery(self) -> None:
        resp = await self._call("kinds")
        self._kinds = dict(resp.get("kinds") or {})
        self._cluster_scoped = set(resp.get("clusterScoped") or [])

    def is_cluster_scoped(self, resource: str) -> bool:
        if self._kinds is not None:
            return resource in self._cluster_scoped
        from kubernetes_tpu.api.meta import CLUSTER_SCOPED_RESOURCES
        return resource in CLUSTER_SCOPED_RESOURCES

    def resource_for_kind(self, kind: str) -> str | None:
        if self._kinds is not None and kind in self._kinds:
            return self._kinds[kind]
        from kubernetes_tpu.api.meta import KIND_TO_RESOURCE
        return KIND_TO_RESOURCE.get(kind)

    def kind_map(self) -> dict[str, str]:
        from kubernetes_tpu.api.meta import KIND_TO_RESOURCE
        merged = dict(KIND_TO_RESOURCE)
        merged.update(self._kinds or {})
        return merged
