"""The API server: the only process that talks to the store (SURVEY L2).

`server.APIServer` serves kube-shaped REST+JSON over an MVCCStore — CRUD,
LIST with selectors/paging, chunked WATCH streams with bookmarks and 410
semantics, subresources (binding), a handler chain with API-Priority-and-
Fairness-lite inflight control, and /metrics / /healthz.

`client.RemoteStore` is the client-side counterpart: it implements the same
interface informers and controllers consume in-process (list/watch/create/
get/update/delete/guaranteed_update/subresource), so every component gains a
remote mode with zero changes — the §3.2 PROCESS BOUNDARY made real.
"""

from kubernetes_tpu.apiserver.client import RemoteStore
from kubernetes_tpu.apiserver.server import APIServer, PriorityLevel

__all__ = ["APIServer", "PriorityLevel", "RemoteStore"]
