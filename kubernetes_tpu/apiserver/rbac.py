"""RBAC-lite authorization for the API server.

Parity target: plugin/pkg/auth/authorizer/rbac (`RBACAuthorizer.Authorize`)
over the rbac.authorization.k8s.io ClusterRole / ClusterRoleBinding shapes,
trimmed to the verb × resource decision the rest of this framework needs
(no apiGroups/resourceNames/nonResourceURLs distinctions; namespaced Role
scoping collapses onto the cluster scope).
"""

from __future__ import annotations

from typing import Iterable, Mapping

#: verbs the request-info middleware produces, plus the impersonation
#: filter's `impersonate` check (resource "users").
VERBS = ("get", "list", "watch", "create", "update", "patch", "delete",
         "impersonate")


def make_cluster_role(name: str, rules: list[Mapping]) -> dict:
    """rbac.authorization.k8s.io/v1 ClusterRole:
    rules entries {"verbs": [...], "resources": [...]}."""
    return {"apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": {"name": name},
            "rules": [dict(r) for r in rules]}


def make_cluster_role_binding(name: str, role: str,
                              users: Iterable[str]) -> dict:
    return {"apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": {"name": name},
            "roleRef": {"kind": "ClusterRole", "name": role},
            "subjects": [{"kind": "User", "name": u} for u in users]}


class RBACAuthorizer:
    """Allow iff some binding grants the user a role whose rules cover
    (verb, resource). Deny-by-default, like the reference."""

    def __init__(self, roles: Iterable[Mapping] = (),
                 bindings: Iterable[Mapping] = ()):
        #: role name -> rules
        self._rules: dict[str, list[dict]] = {}
        #: user -> set of role names ("*" user = everyone)
        self._grants: dict[str, set[str]] = {}
        #: group -> set of role names — kept apart from users so a binding
        #: to Group "admins" never empowers a USER literally named "admins"
        #: (the reference keys its rule index by subject kind too).
        self._group_grants: dict[str, set[str]] = {}
        for r in roles:
            self.add_role(r)
        for b in bindings:
            self.add_binding(b)

    def add_role(self, role: Mapping) -> None:
        self._rules[role["metadata"]["name"]] = [
            dict(r) for r in role.get("rules") or []]

    def add_binding(self, binding: Mapping) -> None:
        role = (binding.get("roleRef") or {}).get("name")
        if not role:
            return
        for subj in binding.get("subjects") or []:
            kind = subj.get("kind")
            name = subj.get("name", "")
            if kind == "Group":
                self._group_grants.setdefault(name, set()).add(role)
            elif kind == "ServiceAccount":
                # SA subjects authenticate as their token username. No
                # namespace ⇒ matches nothing (upstream RBAC ignores such
                # subjects rather than guessing a namespace).
                ns = subj.get("namespace")
                if ns:
                    self._grants.setdefault(
                        f"system:serviceaccount:{ns}:{name}",
                        set()).add(role)
            elif kind in (None, "User"):
                self._grants.setdefault(name, set()).add(role)

    def allowed(self, user: str, verb: str, resource: str,
                groups: Iterable[str] = ()) -> bool:
        roles = self._grants.get(user, set()) | self._grants.get("*", set())
        for g in groups:
            roles = roles | self._group_grants.get(g, set())
        for role in roles:
            for rule in self._rules.get(role, ()):
                verbs = rule.get("verbs") or ()
                resources = rule.get("resources") or ()
                if ("*" in verbs or verb in verbs) and \
                        ("*" in resources or resource in resources):
                    return True
        return False
