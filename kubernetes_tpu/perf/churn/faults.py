"""Deterministic fault scheduler for the churn battery (SURVEY §5.3).

Faults are TIMELINE events, fixed before the run starts:
`build_fault_timeline(specs, seed)` resolves every randomizable choice
(which node dies, which pods roll) at build time with a seeded rng, so
the timeline — offsets, kinds, victims — is bit-identical across runs
with the same seed (the kwok-style hollow-node approach: faults are
staged data, not emergent races).

Kinds (performance-config.yaml `faults:` entries / bench --churn-fault):

- nodeDeath   — kill a NodeAgent (stop(graceful=False): tasks cancelled,
  no further writes, Node + Lease left to go STALE) and let the
  nodelifecycle controller's grace period notice, taint unreachable and
  evict. The injector recreates one replacement per displaced pod (the
  ReplicaSet's job in the reference) and measures time-to-recovery:
  every replacement bound AND queue backlog back under threshold.
- drain       — cordon (spec.unschedulable) + evict the node's pods
  (kubectl drain lifecycle), replacements recreated, recovery measured;
  uncordons at recovery.
- cordon / uncordon — lifecycle-only store writes (no recovery clock).
- rolloutWave — delete `count` bound pods and recreate them stamped with
  a new revision label (a deployment rollout wave's shape mid-churn).
- gangArrival — create `count` pods AT ONCE from `podTemplate` (e.g.
  high-priority, colliding with the r6 preemption and r9 policy paths);
  recovery = the whole gang bound. With `sliceShape: [s0, s1(, s2)]`
  the gang is SLICE-SHAPED (topology/): a PodGroup with that shape is
  created first, every pod carries its group label, count defaults to
  prod(shape), and recovery means the whole gang bound as one
  contiguous sub-mesh (Coscheduling Permit enforces the contiguity).
- sliceDeath — kill a member node out from under a bound slice gang
  (`group` names the gang — a prior gangArrival's `slice-<at_ms>`):
  cordon + agent-kill the first member's node, delete the gang's pods
  and PodGroup, then recreate the gang under `<group>-r<at_ms>` with
  the same `sliceShape`; recovery = the replacement gang RE-COALESCED
  on a fresh contiguous sub-mesh that avoids the dead cell — the
  ChurnSlicePacking family's time-to-re-coalesce headline.
- killLeader  — SIGKILL the ACTIVE scheduler process mid-wave
  (multi-process runs only: needs the injector's `control_plane`
  seam — multiproc/controlplane.py). The standby must win the lease
  by EXPIRY, rebuild its assume-cache from fresh informer LISTs, and
  resume; recovery = `count` canary pods created at kill time all
  bound + backlog under threshold — the end-to-end failover
  time-to-recovery the r22 ChurnDay row records.

Each fault runs as its own task so recovery tracking never delays later
timeline events; `churn_faults_injected_total{kind}` counts injections
in the metrics registry (ChurnMetrics).
"""

from __future__ import annotations

import asyncio
import copy
import logging
import math
import random
import time
from typing import Any, Callable, Mapping

from kubernetes_tpu.api.meta import namespaced_name
from kubernetes_tpu.perf.churn.arrivals import stable_seed
from kubernetes_tpu.store.mvcc import StoreError

logger = logging.getLogger(__name__)

#: recovery polling tick (coarse enough to stay off the hot path, fine
#: enough that sub-second recoveries resolve).
_POLL = 0.02


class FaultEvent:
    """One scheduled fault: `at` seconds after phase start."""

    __slots__ = ("at", "kind", "params")

    def __init__(self, at: float, kind: str, params: dict | None = None):
        self.at = float(at)
        self.kind = kind
        self.params = dict(params or {})

    def signature(self) -> tuple:
        """Deterministic identity (the timeline-equality contract tests
        compare): offset, kind, and the sorted resolved params."""
        return (round(self.at, 9), self.kind,
                tuple(sorted((k, str(v)) for k, v in self.params.items())))

    def __repr__(self) -> str:  # debugging/log readability
        return f"FaultEvent(at={self.at:.3f}, kind={self.kind}, " \
               f"params={self.params})"


def build_fault_timeline(specs: list[Mapping], seed: int = 0,
                         node_names: list[str] | None = None,
                         ) -> list[FaultEvent]:
    """Resolve `faults:` specs into a sorted, fully-determined timeline.

    Randomizable choices (a nodeDeath/drain with no explicit `node`, a
    rolloutWave's victim offset) are fixed HERE with a seeded rng so the
    run replays; `node_names` is the candidate pool (agent-backed node
    names, in boot order)."""
    rng = random.Random(stable_seed("faults", seed,
                                    len(specs), len(node_names or [])))
    events: list[FaultEvent] = []
    for i, spec in enumerate(specs):
        kind = str(spec.get("kind", ""))
        params = {k: v for k, v in spec.items()
                  if k not in ("at", "kind")}
        if kind in ("nodeDeath", "drain", "cordon", "uncordon") \
                and "node" not in params:
            pool = node_names or []
            if not pool:
                raise ValueError(
                    f"fault #{i} ({kind}) needs a node: no agent-backed "
                    "nodes to pick from and no explicit 'node'")
            params["node"] = pool[rng.randrange(len(pool))]
        if kind == "rolloutWave":
            params.setdefault("count", 10)
            # Victim selection offset into the sorted bound set, fixed
            # now so two runs roll the same slice.
            params.setdefault("offset", rng.randrange(1 << 16))
        if kind == "gangArrival":
            shape = params.get("sliceShape")
            params.setdefault(
                "count", math.prod(int(s) for s in shape) if shape else 8)
        if kind == "sliceDeath":
            # Both are identity, not chance: the timeline must say WHICH
            # gang dies and what shape re-coalesces.
            for req in ("group", "sliceShape"):
                if req not in params:
                    raise ValueError(
                        f"fault #{i} (sliceDeath) needs {req!r}")
        if kind == "killLeader":
            # Canary pods probing scheduling liveness across failover.
            params.setdefault("count", 8)
        events.append(FaultEvent(float(spec.get("at", 0.0)), kind, params))
    events.sort(key=lambda e: (e.at, e.kind))
    return events


class FaultInjector:
    """Executes a fault timeline against a live churn run.

    The harness (perf/scheduler_perf.py churnOpenLoop) supplies the run's
    seams: the store, the agent fleet (node death's kill target), the
    informer-fed bound-key set, a replacement-pod factory that rides the
    run's accounting, and the scheduler queue's backlog gauge."""

    def __init__(self, *, store, agents: list,
                 bound_keys: set[str],
                 create_pod: Callable[..., Any],
                 backlog_fn: Callable[[], int],
                 metrics=None,
                 pod_template: Mapping | None = None,
                 recovery_threshold: int = 10,
                 recovery_timeout: float = 60.0,
                 namespace: str = "default",
                 clock: Callable[[], float] = time.monotonic,
                 control_plane=None):
        self.store = store
        self.agents = {a.node_name: a for a in agents}
        self.bound_keys = bound_keys
        self.create_pod = create_pod
        self.backlog_fn = backlog_fn
        self.metrics = metrics
        self.pod_template = dict(pod_template or {})
        self.recovery_threshold = int(recovery_threshold)
        self.recovery_timeout = float(recovery_timeout)
        self.namespace = namespace
        self.clock = clock
        #: MultiProcessControlPlane (multiproc/) or None — the
        #: killLeader seam; in-process runs have no leader to kill.
        self.control_plane = control_plane
        #: one record per injected fault, timeline order:
        #: {kind, at, node?, displaced_pods, replacements, recovery_s,
        #:  recovered}
        self.results: list[dict] = []
        self._tasks: list[asyncio.Task] = []
        #: net pods created minus deleted by fault handlers (the runner
        #: folds this into its created_total so later barriers balance).
        self.net_created = 0

    # -- lifecycle ---------------------------------------------------------

    async def run(self, timeline: list[FaultEvent], t0: float) -> None:
        """Fire every event at its offset (absolute clock anchored at
        t0); handlers run as tasks so one fault's recovery wait never
        delays the next injection. Await `drain()` for the results."""
        for ev in timeline:
            delay = (t0 + ev.at) - self.clock()
            if delay > 0:
                await asyncio.sleep(delay)
            rec = {"kind": ev.kind, "at": round(ev.at, 3),
                   "displaced_pods": 0, "replacements": 0,
                   "recovery_s": None, "recovered": None}
            if "node" in ev.params:
                rec["node"] = ev.params["node"]
            self.results.append(rec)
            if self.metrics is not None:
                self.metrics.faults_injected.inc(kind=ev.kind)
            self._tasks.append(asyncio.ensure_future(
                self._fire(ev, rec)))

    async def drain(self) -> None:
        """Wait for every in-flight fault handler (recovery clocks
        included) — bounded by each handler's own recovery_timeout."""
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()

    async def cancel(self) -> None:
        for t in self._tasks:
            t.cancel()
        await self.drain()

    async def _fire(self, ev: FaultEvent, rec: dict) -> None:
        handler = getattr(self, f"_do_{ev.kind}", None)
        if handler is None:
            logger.error("unknown fault kind %r — skipped", ev.kind)
            rec["recovered"] = False
            return
        try:
            await handler(ev, rec)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("fault %s failed", ev.kind)
            rec["recovered"] = False

    # -- kinds -------------------------------------------------------------

    async def _do_nodeDeath(self, ev: FaultEvent, rec: dict) -> None:
        node = ev.params["node"]
        displaced = await self._pods_on(node)
        agent = self.agents.get(node)
        t_kill = self.clock()
        if agent is not None:
            # The death itself: tasks cancelled, no further writes; the
            # Lease goes stale and the nodelifecycle controller's grace
            # period decides when the cluster notices.
            await agent.stop(graceful=False)
        else:
            # createNodes staging (no agent to kill): the closest honest
            # analog is deleting the Node object outright.
            try:
                await self.store.delete("nodes", node)
            except StoreError:
                pass
        rec["displaced_pods"] = len(displaced)
        await self._replace_and_recover(
            ev, rec, displaced, t_kill,
            wait_eviction=agent is not None)

    async def _do_drain(self, ev: FaultEvent, rec: dict) -> None:
        node = ev.params["node"]
        t0 = self.clock()
        await self._set_unschedulable(node, True)
        displaced = await self._pods_on(node)
        rec["displaced_pods"] = len(displaced)
        for p in displaced:
            try:
                await self.store.delete("pods", namespaced_name(p))
                self.net_created -= 1
            except StoreError:
                pass
        await self._replace_and_recover(ev, rec, displaced, t0,
                                        wait_eviction=False)
        if ev.params.get("uncordon", True):
            await self._set_unschedulable(node, False)

    async def _do_cordon(self, ev: FaultEvent, rec: dict) -> None:
        await self._set_unschedulable(ev.params["node"], True)
        rec["recovered"] = True

    async def _do_uncordon(self, ev: FaultEvent, rec: dict) -> None:
        await self._set_unschedulable(ev.params["node"], False)
        rec["recovered"] = True

    async def _do_rolloutWave(self, ev: FaultEvent, rec: dict) -> None:
        count = int(ev.params["count"])
        bound = sorted(self.bound_keys)
        if not bound:
            rec["recovered"] = True
            return
        start = int(ev.params.get("offset", 0)) % len(bound)
        # stride > 1 scatters the victims across the sorted bound set —
        # name order tracks packing order, so a strided slice punches
        # holes in MANY nodes instead of emptying a contiguous few. With
        # replace=false that is the descheduler's adversary: a canceled
        # rollout (scale-down) stranding survivors on half-empty nodes
        # that arrival-order placement never revisits.
        stride = max(1, int(ev.params.get("stride", 1)))
        victims = list(dict.fromkeys(
            bound[(start + i * stride) % len(bound)]
            for i in range(min(count, len(bound)))))
        t0 = self.clock()
        rec["displaced_pods"] = len(victims)
        for key in victims:
            try:
                await self.store.delete("pods", key)
                self.net_created -= 1
            except StoreError:
                pass
        if not ev.params.get("replace", True):
            rec["replacements"] = 0
            rec["recovered"] = True
            return
        tmpl = {**self.pod_template,
                "labels": {**(self.pod_template.get("labels") or {}),
                           "rollout": f"wave-{round(ev.at * 1e3)}"}}
        names = [f"roll-{round(ev.at * 1e3)}-{i}"
                 for i in range(len(victims))]
        await self._create_many(names, tmpl)
        rec["replacements"] = len(names)
        await self._await_bound(names, rec, t0)

    async def _do_gangArrival(self, ev: FaultEvent, rec: dict) -> None:
        count = int(ev.params["count"])
        tmpl = {**self.pod_template, **(ev.params.get("podTemplate") or {})}
        ns = tmpl.get("namespace", self.namespace)
        shape = ev.params.get("sliceShape")
        if shape:
            # Slice-shaped gang: the PodGroup (with sliceShape) must
            # exist BEFORE the pods so Coscheduling/TopologySlice see a
            # resolvable group from the first attempt.
            group = str(ev.params.get("group",
                                      f"slice-{round(ev.at * 1e3)}"))
            tmpl = await self._create_slice_group(group, shape, tmpl, ns)
            names = [f"{group}-{i}" for i in range(count)]
        else:
            names = [f"gang-{round(ev.at * 1e3)}-{i}" for i in range(count)]
        t0 = self.clock()
        await self._create_many(names, tmpl)
        rec["replacements"] = count
        # The gang may land in the fault template's own namespace — the
        # bound-key wait must watch THAT one, not the injector default.
        await self._await_bound(names, rec, t0, namespace=ns)

    async def _do_sliceDeath(self, ev: FaultEvent, rec: dict) -> None:
        from kubernetes_tpu.scheduler.plugins.coscheduling import (
            POD_GROUP_LABEL,
        )
        group = str(ev.params["group"])
        shape = [int(s) for s in ev.params["sliceShape"]]
        tmpl = {**self.pod_template, **(ev.params.get("podTemplate") or {})}
        ns = tmpl.get("namespace", self.namespace)
        try:
            pods = (await self.store.list("pods")).items
        except StoreError:
            pods = []
        members = sorted(
            (p for p in pods
             if (p.get("metadata", {}).get("labels") or {})
             .get(POD_GROUP_LABEL) == group
             and p.get("metadata", {}).get("namespace", "default") == ns),
            key=lambda p: p["metadata"]["name"])
        if not members:
            logger.error("sliceDeath: gang %s has no pods — skipped", group)
            rec["recovered"] = False
            return
        # Kill the first member's node: cordon (the scheduler must not
        # re-place onto the corpse — the bench has no kubelet ack, so an
        # un-cordoned dead node would still "bind") and stop its agent.
        victim = next((p["spec"].get("nodeName") for p in members
                       if p["spec"].get("nodeName")), None)
        t_kill = self.clock()
        if victim is not None:
            rec["node"] = victim
            await self._set_unschedulable(victim, True)
            agent = self.agents.get(victim)
            if agent is not None:
                await agent.stop(graceful=False)
        rec["displaced_pods"] = len(members)
        for p in members:
            try:
                await self.store.delete("pods", namespaced_name(p))
                self.net_created -= 1
            except StoreError:
                pass
        try:
            await self.store.delete("podgroups", f"{ns}/{group}")
        except StoreError:
            pass
        # Re-coalesce: the same shape under a fresh group name must find
        # a contiguous sub-mesh that routes around the dead cell.
        regroup = f"{group}-r{round(ev.at * 1e3)}"
        tmpl = await self._create_slice_group(regroup, shape, tmpl, ns)
        names = [f"{regroup}-{i}" for i in range(math.prod(shape))]
        await self._create_many(names, tmpl)
        rec["replacements"] = len(names)
        await self._await_bound(names, rec, t_kill, namespace=ns)

    async def _create_slice_group(self, group: str, shape, tmpl: Mapping,
                                  ns: str) -> dict:
        """Create the slice-shaped PodGroup and return the pod template
        stamped with its membership label."""
        from kubernetes_tpu.scheduler.plugins.coscheduling import (
            POD_GROUP_LABEL,
            make_pod_group,
        )
        count = math.prod(int(s) for s in shape)
        try:
            await self.store.create("podgroups", make_pod_group(
                group, min_member=count, namespace=ns, slice_shape=shape))
        except StoreError:
            logger.warning("slice gang PodGroup %s create failed", group)
        return {**tmpl,
                "labels": {**(tmpl.get("labels") or {}),
                           POD_GROUP_LABEL: group}}

    async def _do_killLeader(self, ev: FaultEvent, rec: dict) -> None:
        cp = self.control_plane
        if cp is None:
            logger.error("killLeader fault needs a multi-process run "
                         "(--processes >= 2) — skipped")
            rec["recovered"] = False
            return
        t0 = self.clock()
        killed = await cp.kill_leader()
        rec["leader"] = killed
        if killed is None:
            # No replica held the lease (already mid-election):
            # nothing to kill, nothing to recover.
            rec["recovered"] = False
            return
        # Canary gang created AT kill time: they can only bind once the
        # standby holds the lease and has rebuilt its assume-cache, so
        # their time-to-bound IS the failover time-to-recovery.
        count = int(ev.params.get("count", 8))
        names = [f"failover-{round(ev.at * 1e3)}-{i}"
                 for i in range(count)]
        await self._create_many(names, self.pod_template)
        rec["replacements"] = count
        await self._await_bound(names, rec, t0)

    async def _pods_on(self, node: str) -> list[dict]:
        try:
            lst = await self.store.list(
                "pods", fields={"spec.nodeName": node})
            return list(lst.items)
        except StoreError:
            return []

    async def _set_unschedulable(self, node: str, value: bool) -> None:
        def mutate(obj):
            if value:
                obj.setdefault("spec", {})["unschedulable"] = True
            else:
                obj.get("spec", {}).pop("unschedulable", None)
            return obj
        try:
            await self.store.guaranteed_update(
                "nodes", node, mutate, return_copy=False)
        except StoreError:
            pass

    async def _create_many(self, names: list[str], tmpl: Mapping) -> None:
        for name in names:
            try:
                await self.create_pod(name, copy.deepcopy(dict(tmpl)))
                self.net_created += 1
            except StoreError:
                logger.warning("fault replacement create %s failed", name)

    async def _replace_and_recover(self, ev: FaultEvent, rec: dict,
                                   displaced: list[dict],
                                   t0: float, *,
                                   wait_eviction: bool) -> None:
        """The ReplicaSet's half of recovery: once a displaced pod's
        eviction delete lands (observed via the bound-key set), recreate
        a replacement; recovery = every replacement bound + backlog back
        under threshold."""
        keys = [namespaced_name(p) for p in displaced]
        deadline = t0 + self.recovery_timeout
        if wait_eviction and keys:
            # Node death: eviction is the lifecycle controller's move
            # (taint after grace, evict after tolerationSeconds).
            while any(k in self.bound_keys for k in keys) \
                    and self.clock() < deadline:
                await asyncio.sleep(_POLL)
            self.net_created -= sum(
                1 for k in keys if k not in self.bound_keys)
        suffix = f"r{round(ev.at * 1e3)}"
        names = [f"{k.rsplit('/', 1)[-1]}-{suffix}" for k in keys]
        await self._create_many(names, self.pod_template)
        rec["replacements"] = len(names)
        await self._await_bound(names, rec, t0, deadline=deadline)

    async def _await_bound(self, names: list[str], rec: dict,
                           t0: float, deadline: float | None = None,
                           namespace: str | None = None) -> None:
        want = {f"{namespace or self.namespace}/{n}" for n in names}
        if deadline is None:
            deadline = t0 + self.recovery_timeout
        while self.clock() < deadline:
            if want <= self.bound_keys \
                    and self.backlog_fn() <= self.recovery_threshold:
                dt = self.clock() - t0
                rec["recovery_s"] = round(dt, 3)
                rec["recovered"] = True
                if self.metrics is not None:
                    self.metrics.recovery_seconds.inc(dt, kind=rec["kind"])
                return
            await asyncio.sleep(_POLL)
        rec["recovered"] = False
