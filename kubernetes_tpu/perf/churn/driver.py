"""Open-loop churn driver + rate-sweep (knee) harness.

The driver walks a precomputed arrival timeline on an ABSOLUTE clock
anchored at phase start: each pod create fires at `t0 + offset` whether
or not earlier pods scheduled. Creates are spawned, not awaited inline —
awaiting each write would close the loop through the transport and turn
saturation into a slower arrival clock instead of queue growth (the
failure mode the drain families can't see). A backlog sampler rides
along, feeding the `scheduler_pending_pods{queue}` gauge and recording
the peak/final depth that the knee test reads.

The sweep harness (`run_rate_sweep`) runs one workload per arrival rate
and reports, per row, the exact p50/p99/p999 attempt latency (r11's
WindowedLatencyRecorder via the measured window) with queue growth as
the saturation witness; `find_knee` names the highest offered rate the
scheduler absorbed (backlog at window end under `saturation_frac` of
the window's offered arrivals) and the first rate it didn't.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Callable, Mapping

from kubernetes_tpu.perf.churn.arrivals import ArrivalProcess

logger = logging.getLogger(__name__)

#: in-flight create tasks above this high-water mark fall back to an
#: awaited create: a memory backstop, not pacing (hit only when the
#: TRANSPORT — not the scheduler — is the bottleneck; counted so a run
#: that degraded open-loop honesty says so in its result).
_MAX_INFLIGHT_CREATES = 10_000


class ChurnPhaseResult:
    """What one open-loop phase measured (folded into WorkloadResult)."""

    def __init__(self):
        self.offered_rate = 0.0       # the arrival process's target
        self.achieved_rate = 0.0      # arrivals actually enqueued / wall
        self.arrivals_total = 0
        self.arrival_model = ""
        self.duration = 0.0
        self.backlog_peak = 0
        self.backlog_final = 0
        self.pending_final: dict[str, int] = {}
        self.late_arrivals = 0        # fired >50ms past their offset
        self.throttled_creates = 0    # backstop-awaited (transport-bound)
        self.create_errors = 0
        #: seconds spent draining in-flight create tasks AFTER the
        #: window closed — nonzero means the TRANSPORT (not the
        #: scheduler) lagged the arrival clock.
        self.create_drain_s = 0.0


class ChurnDriver:
    """Drives one open-loop arrival phase against a live run."""

    def __init__(self, process: ArrivalProcess, duration: float, *,
                 create_pod: Callable[[str], Any],
                 backlog_stats: Callable[[], Mapping[str, int]],
                 on_backlog: Callable[[Mapping[str, int]], None]
                 | None = None,
                 metrics=None,
                 name_prefix: str = "churn",
                 sample_period: float = 0.05,
                 clock: Callable[[], float] = time.monotonic):
        self.process = process
        self.duration = float(duration)
        self.create_pod = create_pod
        self.backlog_stats = backlog_stats
        self.on_backlog = on_backlog
        self.metrics = metrics
        self.name_prefix = name_prefix
        self.sample_period = sample_period
        self.clock = clock
        self.result = ChurnPhaseResult()

    async def run(self, t0: float | None = None) -> ChurnPhaseResult:
        res = self.result
        res.offered_rate = self.process.rate
        res.arrival_model = self.process.kind
        res.duration = self.duration
        timeline = self.process.timeline(self.duration)
        if t0 is None:
            t0 = self.clock()
        pending: set[asyncio.Task] = set()
        sampler = asyncio.ensure_future(self._sample_backlog(t0))
        seq = 0
        loop_end = None
        try:
            for offset in timeline:
                delay = (t0 + offset) - self.clock()
                if delay > 0:
                    await asyncio.sleep(delay)
                elif delay < -0.05:
                    res.late_arrivals += 1
                name = f"{self.name_prefix}-{seq}"
                seq += 1
                if len(pending) >= _MAX_INFLIGHT_CREATES:
                    res.throttled_creates += 1
                    await self._create(name)
                else:
                    t = asyncio.ensure_future(self._create(name))
                    pending.add(t)
                    t.add_done_callback(pending.discard)
            # Phase runs to its full duration even if the last arrival
            # landed early: the window's percentiles cover steady state,
            # not an arrival-truncated prefix.
            tail = (t0 + self.duration) - self.clock()
            if tail > 0:
                await asyncio.sleep(tail)
            # WINDOW-END accounting, before the create drain below:
            # offered work not yet absorbed = the scheduler's queue
            # PLUS creates still in the transport — counting only the
            # former would let a slow wire masquerade as headroom.
            loop_end = self.clock()
            stats = dict(self.backlog_stats())
            res.pending_final = stats
            res.backlog_final = sum(stats.values()) + len(pending)
            res.backlog_peak = max(res.backlog_peak, res.backlog_final)
        finally:
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
            sampler.cancel()
            try:
                await sampler
            except (asyncio.CancelledError, Exception):
                pass
        res.arrivals_total = seq
        end = loop_end if loop_end is not None else self.clock()
        res.create_drain_s = max(self.clock() - end, 0.0)
        # Achieved rate is measured at pacing-loop end: the arrival
        # clock is what's open-loop, not create completion.
        res.achieved_rate = seq / max(end - t0, 1e-9)
        if self.metrics is not None:
            self.metrics.arrivals.inc(seq, model=self.process.kind)
            self.metrics.backlog_peak.set(res.backlog_peak)
        return res

    async def _create(self, name: str) -> None:
        try:
            await self.create_pod(name)
        except asyncio.CancelledError:
            raise
        except Exception:
            self.result.create_errors += 1
            logger.exception("churn arrival create %s failed", name)

    async def _sample_backlog(self, t0: float) -> None:
        """Keep scheduler_pending_pods fresh while the loop is saturated
        (the scheduler only refreshes it per popped batch) and track the
        peak the knee detection reads."""
        while True:
            await asyncio.sleep(self.sample_period)
            stats = dict(self.backlog_stats())
            self.result.backlog_peak = max(self.result.backlog_peak,
                                           sum(stats.values()))
            if self.on_backlog is not None:
                self.on_backlog(stats)


# -- rate sweep / knee ----------------------------------------------------


def is_saturated(arrivals_total: int, backlog_final: int,
                 saturation_frac: float = 0.2,
                 offered_rate: float | None = None,
                 achieved_rate: float | None = None) -> bool:
    """The one saturation rule (single runs and the knee sweep share
    it). Two witnesses, either suffices:

    - queue growth: at window end the backlog (scheduler tiers PLUS
      in-flight creates) holds more than `saturation_frac` of
      everything the window offered — fraction-of-offered is
      duration-invariant (a seconds-of-work rule degenerates when the
      window is shorter than the horizon it measures in);
    - clock slip: the driver could not even FIRE arrivals at half the
      offered rate (event loop / transport jammed) — the rate is
      beyond the system, harness included, whatever the queue shows.
    """
    if backlog_final > max(saturation_frac * arrivals_total, 16.0):
        return True
    return bool(offered_rate and achieved_rate is not None
                and achieved_rate < 0.5 * offered_rate)


def find_knee(rows: list[Mapping], saturation_frac: float = 0.2) -> dict:
    """Pick the knee from sweep rows (each needs churn_offered_rate,
    churn_arrivals_total and churn_backlog_final).

    A row is SATURATED per is_saturated — open-loop arrivals mean
    backlog growth IS the saturation signal (p-latency alone can't
    distinguish "slow but keeping up" from "diverging").
    Knee = highest non-saturated offered rate; the first saturated rate
    above it bounds the knee from above."""
    annotated = []
    for row in sorted(rows, key=lambda r: r["churn_offered_rate"]):
        rate = row["churn_offered_rate"]
        saturated = is_saturated(row["churn_arrivals_total"],
                                 row["churn_backlog_final"],
                                 saturation_frac,
                                 offered_rate=rate,
                                 achieved_rate=row.get(
                                     "churn_achieved_rate"))
        annotated.append((rate, saturated, row))
    # Highest non-saturated row WHEREVER it sits: saturation need not be
    # monotonic in rate (the trickle regime's un-amortized dispatch can
    # trip the threshold at LOW rates while mid rates absorb fine), and
    # an absorbed rate must never be reported as "no knee".
    knee = None
    for rate, saturated, row in annotated:
        if not saturated:
            knee = row
    knee_rate = knee["churn_offered_rate"] if knee else None
    # The knee's upper bound: the lowest saturated rate ABOVE it (a
    # saturated trickle row below the knee is the dispatch pathology,
    # not the knee's bracket).
    first_saturated = None
    for rate, saturated, row in annotated:
        if saturated and (knee_rate is None or rate > knee_rate):
            first_saturated = row
            break
    return {
        "knee_rate": knee["churn_offered_rate"] if knee else None,
        "knee_p999_ms": knee.get("attempt_p999_ms") if knee else None,
        "knee_p99_ms": knee.get("attempt_p99_ms") if knee else None,
        "knee_p50_ms": knee.get("attempt_p50_ms") if knee else None,
        "first_saturated_rate":
            first_saturated["churn_offered_rate"]
            if first_saturated else None,
        "saturation_frac": saturation_frac,
    }


def churn_template(*, nodes: int, rate: float, duration: float,
                   seed: int, model: str = "poisson",
                   warmup: int = 0, agents: bool = False,
                   faults: list | None = None,
                   grace: float = 2.0, toleration: float = 0.25,
                   recovery_threshold: int = 10,
                   recovery_timeout: float = 60.0,
                   saturation_frac: float = 0.2,
                   lease_period: float | None = None) -> list[dict]:
    """One ChurnDay workload template: stage nodes (agent-backed when
    faults need a kill target), warm the jit caches with a drained
    burst, then the measured open-loop phase.

    lease_period None auto-scales with fleet size (~nodes/400 s,
    floor 0.5) so heartbeat traffic stays bounded, and the effective
    grace period is floored at 3× the lease — a lease period at or
    above the grace period makes every HEALTHY node flap unreachable
    between renewals (detection time therefore scales with fleet size
    here, exactly as production grace periods do)."""
    if lease_period is None:
        lease_period = min(max(0.5, nodes / 400.0), 10.0)
    grace = max(grace, 3.0 * lease_period)
    stage = {"opcode": "startAgents", "count": nodes,
             "leasePeriod": lease_period} if agents else \
            {"opcode": "createNodes", "count": nodes}
    ops: list[dict] = [stage]
    if warmup:
        ops += [{"opcode": "createPods", "count": warmup},
                {"opcode": "barrier"}]
    churn_op = {
        "opcode": "churnOpenLoop", "collectMetrics": True,
        "arrival": {"model": model, "rate": rate},
        "duration": duration, "seed": seed,
        "recoveryThreshold": recovery_threshold,
        # One threshold for BOTH verdicts: the row's churn_saturated
        # flag and find_knee must never contradict each other.
        "saturationFrac": saturation_frac,
    }
    if faults:
        churn_op["faults"] = list(faults)
        churn_op["nodeGracePeriod"] = grace
        churn_op["tolerationSeconds"] = toleration
        churn_op["recoveryTimeout"] = recovery_timeout
    ops.append(churn_op)
    return ops


def run_rate_sweep(*, nodes: int, rates: list[float], duration: float,
                   seed: int = 17, model: str = "poisson",
                   warmup: int = 0, agents: bool = False,
                   fault: Mapping | None = None, fault_rate: float | None = None,
                   grace: float = 2.0, toleration: float = 0.25,
                   recovery_threshold: int = 10,
                   recovery_timeout: float = 60.0,
                   saturation_frac: float = 0.2,
                   runner_factory: Callable[[], Any] | None = None,
                   timeout: float = 600.0) -> dict:
    """Walk arrival rate to the knee, then (optionally) rerun one rate
    with a fault injected mid-wave. One PerfRunner run per rate — fresh
    store/scheduler/backend each, like run_suite — so rows are
    independent measurements.

    Returns {"rows": [detail dicts], "knee": find_knee(...),
             "fault_row": detail dict | None}."""
    from kubernetes_tpu.perf.scheduler_perf import PerfRunner

    def default_runner():
        return PerfRunner()

    make_runner = runner_factory or default_runner
    rows: list[dict] = []
    for rate in rates:
        template = churn_template(
            nodes=nodes, rate=rate, duration=duration, seed=seed,
            model=model, warmup=warmup, agents=agents,
            recovery_threshold=recovery_threshold,
            saturation_frac=saturation_frac)
        res = asyncio.run(make_runner().run(template, {}, timeout=timeout))
        rows.append(res.as_dict())
    knee = find_knee(rows, saturation_frac=saturation_frac)
    fault_row = None
    if fault is not None:
        rate = float(fault_rate if fault_rate is not None
                     else (knee["knee_rate"] or rates[0]))
        template = churn_template(
            nodes=nodes, rate=rate, duration=duration, seed=seed,
            model=model, warmup=warmup, agents=True,
            faults=[dict(fault)], grace=grace, toleration=toleration,
            recovery_threshold=recovery_threshold,
            recovery_timeout=recovery_timeout,
            saturation_frac=saturation_frac)
        res = asyncio.run(make_runner().run(template, {}, timeout=timeout))
        fault_row = res.as_dict()
    return {"rows": rows, "knee": knee, "fault_row": fault_row}
