"""ChurnDay: open-loop sustained-churn scenario battery (ROADMAP #2).

Every drain family measures a bulk drain of pre-created pods; production
control planes live in steady state — trickling arrivals, rollouts, node
deaths and preemption colliding mid-wave (SURVEY §3.1). This package is
the measurement subsystem for that regime:

- arrivals.py  — seeded open-loop arrival processes (Poisson/burst/ramp):
  pods are enqueued at a target rate regardless of completion, so
  saturation shows up as queue growth, not a slower clock.
- faults.py    — deterministic fault scheduler: timeline events injected
  mid-wave (node death via agent kill + lease expiry, drain/cordon,
  rollout waves, gang arrivals) with time-to-recovery measured.
- driver.py    — the open-loop driver + the rate-sweep harness that
  walks arrival rate to find the knee, reporting exact p50/p99/p999
  attempt latency (r11's WindowedLatencyRecorder) as the headline.
"""

from kubernetes_tpu.perf.churn.arrivals import (
    ArrivalProcess,
    BurstArrivals,
    PoissonArrivals,
    RampArrivals,
    make_arrival_process,
)
from kubernetes_tpu.perf.churn.driver import (
    ChurnDriver,
    find_knee,
    is_saturated,
    run_rate_sweep,
)
from kubernetes_tpu.perf.churn.faults import (
    FaultEvent,
    FaultInjector,
    build_fault_timeline,
)

__all__ = [
    "ArrivalProcess", "PoissonArrivals", "BurstArrivals", "RampArrivals",
    "make_arrival_process", "ChurnDriver", "find_knee", "is_saturated",
    "run_rate_sweep",
    "FaultEvent", "FaultInjector", "build_fault_timeline",
]
