"""Seeded open-loop arrival processes for the churn battery.

An arrival process is a pure function of (spec, seed): `timeline(duration)`
returns the sorted list of arrival offsets (seconds from phase start) and
is bit-identical across runs — the determinism contract the driver and the
fault scheduler share (tests/test_churn_battery.py pins it). The driver
enqueues a pod at each offset on an ABSOLUTE clock anchored at phase
start: a saturated scheduler never slows arrivals down, it only grows the
queue (open-loop, unlike the drain families whose create windows are
implicitly closed-loop behind barriers).

Models (performance-config.yaml `arrival:` spec / bench --churn-model):

- poisson: homogeneous Poisson at `rate` arrivals/s (exponential gaps) —
  the steady-state trickle.
- burst:   all arrivals come in bursts of `burstSize` every
  burstSize/rate seconds (same mean rate, maximally bunched) — informer
  storms and controller sync waves look like this.
- ramp:    inhomogeneous Poisson ramping linearly from `rate` to
  `endRate` over the phase — the knee walked inside ONE run.
"""

from __future__ import annotations

import hashlib
import math
import random
from typing import Mapping


def stable_seed(*parts) -> int:
    """Deterministic rng seed from mixed parts: sha256 of the repr
    string, NOT hash() (str hashes are randomized per process, which
    would silently break the cross-run bit-identical contract)."""
    h = hashlib.sha256("|".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:8], "big")


class ArrivalProcess:
    """Base: subclasses fill `kind` and `_generate(rng, duration)`."""

    kind = "arrival"

    def __init__(self, rate: float, seed: int = 0):
        if rate <= 0:
            raise ValueError(f"arrival rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.seed = int(seed)

    def timeline(self, duration: float) -> list[float]:
        """Sorted arrival offsets in [0, duration). Deterministic: a fresh
        seeded rng per call, so repeated calls (and re-runs) are
        bit-identical."""
        rng = random.Random(
            stable_seed(self.kind, self.seed, self.rate, duration))
        out = self._generate(rng, float(duration))
        assert all(0.0 <= t < duration for t in out)
        return out

    def _generate(self, rng: random.Random,
                  duration: float) -> list[float]:
        raise NotImplementedError


class PoissonArrivals(ArrivalProcess):
    kind = "poisson"

    def _generate(self, rng: random.Random,
                  duration: float) -> list[float]:
        out: list[float] = []
        t = rng.expovariate(self.rate)
        while t < duration:
            out.append(t)
            t += rng.expovariate(self.rate)
        return out


class BurstArrivals(ArrivalProcess):
    """Bursts of `burst_size` simultaneous arrivals every
    burst_size/rate seconds: the mean rate matches Poisson at the same
    `rate`, but the queue sees the worst-case bunching."""

    kind = "burst"

    def __init__(self, rate: float, seed: int = 0, burst_size: int = 32):
        super().__init__(rate, seed)
        self.burst_size = max(1, int(burst_size))

    def _generate(self, rng: random.Random,
                  duration: float) -> list[float]:
        period = self.burst_size / self.rate
        out: list[float] = []
        t = 0.0
        while t < duration:
            out.extend([t] * self.burst_size)
            t += period
        return out


class RampArrivals(ArrivalProcess):
    """Linear rate ramp rate → end_rate over the phase, realized as an
    inhomogeneous Poisson process by inversion: unit-exponential gaps in
    cumulative-intensity space Λ(t) = r0·t + (r1−r0)·t²/(2D), mapped
    back through the quadratic root."""

    kind = "ramp"

    def __init__(self, rate: float, seed: int = 0,
                 end_rate: float | None = None):
        super().__init__(rate, seed)
        self.end_rate = float(end_rate if end_rate is not None
                              else 4 * rate)
        if self.end_rate <= 0:
            raise ValueError("ramp endRate must be > 0")

    def _generate(self, rng: random.Random,
                  duration: float) -> list[float]:
        r0, r1, dur = self.rate, self.end_rate, duration
        slope = (r1 - r0) / dur
        out: list[float] = []
        lam = rng.expovariate(1.0)
        while True:
            if abs(slope) < 1e-12:
                t = lam / r0
            else:
                disc = r0 * r0 + 2 * slope * lam
                if disc < 0:
                    # Ramp-DOWN only: Λ is concave, so a Λ beyond its
                    # reachable maximum has no root — no more arrivals
                    # fit in the window (naively sqrt'ing raised a
                    # math domain error here).
                    return out
                # Solve slope/2·t² + r0·t − Λ = 0 for the positive root.
                t = (-r0 + math.sqrt(disc)) / slope
            if t >= dur:
                return out
            out.append(t)
            lam += rng.expovariate(1.0)


def make_arrival_process(spec: Mapping, seed: int = 0) -> ArrivalProcess:
    """Build a process from a workload-YAML `arrival:` spec:
    {model: poisson|burst|ramp, rate: N, burstSize: N, endRate: N}."""
    model = str(spec.get("model", "poisson"))
    rate = float(spec["rate"])
    if model == "poisson":
        return PoissonArrivals(rate, seed)
    if model == "burst":
        return BurstArrivals(rate, seed,
                             burst_size=int(spec.get("burstSize", 32)))
    if model == "ramp":
        return RampArrivals(rate, seed, end_rate=spec.get("endRate"))
    raise ValueError(f"unknown arrival model {model!r}")
