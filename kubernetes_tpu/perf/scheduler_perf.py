"""scheduler_perf: the reference's scale benchmark harness, YAML-compatible.

Parity target: test/integration/scheduler_perf/ (scheduler_perf.go,
config/performance-config.yaml — SURVEY §3.5). Same trick: in-process
control plane, **no kubelets** — Node objects are data, pods "run" because
nothing contradicts Bind. Same workload YAML shape:

    - name: SchedulingBasic
      workloadTemplate:
      - opcode: createNodes
        countParam: $initNodes
        nodeTemplate: {...}            # inline instead of nodeTemplatePath
      - opcode: createPods
        countParam: $initPods
        podTemplate: {...}
      - opcode: createPods
        countParam: $measurePods
        collectMetrics: true           # the measured phase
      - opcode: barrier                # wait until all created pods scheduled
      workloads:
      - name: 100Nodes
        params: {initNodes: 100, initPods: 500, measurePods: 1000}

Opcodes: createNodes, createPods, barrier, sleep, churn (delete/recreate a
slice of pods for queue pressure), startAgents (N in-process NodeAgents —
hollow kubelets with field-selector pod watches — register their own
Nodes in place of kwok-style data staging, so the run carries the
control-plane cost of N watch consumers + mark-Running writes + lease
heartbeats), relistStorm (every started agent tears down its watch and
cold-start relists AT ONCE — the watch-cache tier's measured scenario:
N reads of one shared snapshot instead of N store scans), churnOpenLoop
(the ChurnDay battery, perf/churn: a TIMED open-loop arrival window —
seeded Poisson/burst/ramp pod arrivals on an absolute clock with an
optional deterministic fault timeline injected mid-wave; saturation
shows up as queue growth, the exact p50/p99/p999 attempt percentiles
are the headline, and disruptive faults report time-to-recovery).
Metrics collected over the measured phase:
SchedulingThroughput (pods/s), scheduling_attempt_duration percentiles
(p50/p90/p99 from the scheduler's own histogram — SURVEY §5.5 names),
node fragmentation % (mean free-capacity fraction; the bin-packing
quality metric BASELINE tracks), and the backend's device-residency
counters (host_fallback_pods / spread_poisoned_pods).
"""

from __future__ import annotations

import asyncio
import copy
import json
import time
from typing import Any, Mapping

from kubernetes_tpu.api.meta import namespaced_name
from kubernetes_tpu.api.types import (make_node, make_pod,
                                      split_node_topology)
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.metrics.registry import SchedulerMetrics
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def _subst(value: Any, params: Mapping[str, Any]) -> Any:
    """$param substitution (countParam etc.)."""
    if isinstance(value, str) and value.startswith("$"):
        return params[value[1:]]
    return value


def _resolve_count(op: Mapping, params: Mapping[str, Any]) -> int:
    if "countParam" in op:
        return int(_subst(op["countParam"], params))
    return int(op.get("count", 0))


class WorkloadResult:
    def __init__(self):
        self.throughput = 0.0          # pods/s over the measured phase
        self.measured_pods = 0
        self.measured_seconds = 0.0
        self.attempt_p50 = 0.0
        self.attempt_p90 = 0.0
        self.attempt_p99 = 0.0
        #: exact-only (the 16-bucket histogram cannot resolve it): true
        #: p999 attempt latency over the measured window — the ROADMAP #3
        #: churn-battery headline percentile.
        self.attempt_p999 = float("nan")
        #: True when p50/p90/p99/p999 came from the exact windowed
        #: recorder (raw order statistics) rather than bucket edges.
        self.attempt_percentiles_exact = False
        self.fragmentation_pct = 0.0
        self.scheduled_total = 0
        self.unschedulable_total = 0
        #: DropIfChannelFull accounting (bounded event broadcaster): a
        #: burst silently shedding most of its "Scheduled" events is a
        #: result property, not stderr noise.
        self.events_emitted_total = 0
        self.events_dropped_total = 0
        #: Device-residency accounting over the measured phase (TPU
        #: backend degradation counters): pods that took per-pod host
        #: plugin rows, and spread pods that missed the union scan table.
        #: A residency regression shows up HERE per run, not just in a
        #: stderr warning.
        self.host_fallback_pods = 0
        self.spread_poisoned_pods = 0
        #: Watch-dispatch efficiency over the measured phase (the store's
        #: interned selector index — metrics/registry.py WatchMetrics):
        #: deliveries vs predicate evaluations. checks staying O(events)
        #: while watcher count grows is the index working; a regression
        #: to O(events × watchers) shows up here as data.
        self.watch_events_dispatched_total = 0
        self.watch_predicate_checks_total = 0
        #: Watch-cache serving-tier accounting over the measured phase
        #: (store/cacher.py): LIST/watch-establishment requests served
        #: from the RV-snapshotted cache vs handed to the mvcc core. A
        #: relist storm that stays all-hits is the tier working.
        self.watch_cache_hits_total = 0
        self.watch_cache_misses_total = 0
        #: relistStorm opcode results: wall time for every agent to tear
        #: down its watch, LIST (off the shared snapshot) and re-watch at
        #: once, plus the storm's own cache hit/miss deltas.
        self.relist_storm_agents = 0
        self.relist_storm_seconds = 0.0
        self.relist_storm_cache_hits = 0
        self.relist_storm_cache_misses = 0
        #: Policy-chain accounting over the measured phase
        #: (policy/vap.py + policy/audit.py): expression evaluations and
        #: audit stage events. A policy-chain regression (policies
        #: silently not evaluating, audit silently shedding) is DATA in
        #: the detail JSON, not stderr noise. The index triple is the
        #: O(matching) dispatch witness: hits = candidates served from
        #: the (resource, operation) exact map, residue = wildcard
        #: entries still scanned linearly, rebuilds = invalidations that
        #: actually cost a rebuild. Audit drops ride the same drop
        #: accounting the event recorder reports.
        self.policy_evaluations_total = 0
        self.audit_events_total = 0
        self.audit_events_dropped_total = 0
        self.policy_index_hits_total = 0
        self.policy_index_residue_scans_total = 0
        self.policy_index_rebuilds_total = 0
        #: Solve-side accounting over the measured phase (the r8 50k
        #: profile's 98%-idle blind spot made data): chunk count and
        #: total device-solve wall (the fused solve as the consumer sees
        #: it — scheduler_tpu_solve_seconds), the per-step scan width of
        #: the last chunk (K + P when the shortlist prunes, N when not),
        #: and the shortlist's exactness-fallback counters.
        self.solver_solve_chunks = 0
        self.solver_solve_seconds_total = 0.0
        self.solver_scan_width = 0
        self.solver_shortlist_pods_total = 0
        self.solver_shortlist_fallbacks_total = 0
        #: Block-index accounting (ISSUE 20): (class, block) pairs the
        #: bound scan walked vs proved losers over the measured phase —
        #: the prune rate is the sublinearity witness the 200k/1m rows
        #: report next to solver_solve_seconds_total.
        self.solver_blocks_scanned_total = 0
        self.solver_blocks_pruned_total = 0
        #: Wavefront-solve accounting over the measured phase (r18): the
        #: wave width of the latest chunk and the speculative-commit vs
        #: serial-replay split — the replay fraction the AdaptiveTuner's
        #: width policy keys on is recorded per run, not inferred.
        self.solver_wave_width = 0
        self.solver_wave_commits_total = 0
        self.solver_wave_replays_total = 0
        #: Fused Pallas wavefront kernel accounting (r21): chunks solved
        #: through ops/pallas_kernel.py vs chunks that requested the
        #: kernel and fell back to the lax.scan reference, plus the
        #: solve-backend provenance row (jax platform, device count,
        #: resolved pallas mode, carry donation) stamped per family so a
        #: relay row and a CPU row are never mistaken for each other.
        self.solver_pallas_solves_total = 0
        self.solver_pallas_fallbacks_total = 0
        self.solve_provenance: dict = {}
        #: Class-dictionary device-plane accounting over the measured
        #: phase (r14): host-side chunk-prep wall (the prep-vs-solve
        #: split per family), equivalence classes behind the latest
        #: chunk's planes, plane payload bytes actually uploaded, and
        #: pods that rode a per-pod fallback after class overflow.
        self.prep_seconds_total = 0.0
        self.plane_classes_per_chunk = 0
        self.plane_bytes_uploaded_total = 0
        self.class_split_fallback_pods = 0
        #: Sharded-control-plane accounting (ROADMAP #5): the run's
        #: shard count (1 = classic single store), per-shard host-prep
        #: rebuilds over the measured phase (the incremental path keeps
        #: this at dirty-shards-only), the solve wall attributed to the
        #: sharded path, and the top-level cross-shard argmax steps.
        self.shard_count = 1
        self.shard_tensor_rebuilds_total = 0
        self.shard_solve_seconds = 0.0
        self.cross_shard_reductions_total = 0
        #: Multi-process control plane accounting (r22 tentpole): OS
        #: processes behind the run (1 = the classic in-process tree —
        #: the structural-degrade witness), WAL appends / replayed
        #: entries / fsync wall summed across the shard apiserver
        #: processes, and scheduler leader elections observed (1 = the
        #: initial acquisition; >1 means a failover happened mid-run).
        self.process_count = 1
        self.wal_appends_total = 0
        self.wal_replay_entries_total = 0
        self.wal_fsync_seconds_total = 0.0
        self.leader_elections_total = 0
        #: Serving-tier accounting over the measured phase
        #: (kubernetes_tpu/serving, ROADMAP #3): lone pods placed
        #: through the pinned C=1 fast path, dispatches whose admission
        #: window merged extra pods, resident device-plane refreshes
        #: (count + wall of the O(changed) scatter), and the admission
        #: window the tier last applied. Zeros under KTPU_SERVING=0 —
        #: the structural-degrade witness.
        self.serving_fast_path_pods_total = 0
        self.serving_coalesced_batches_total = 0
        self.resident_plane_refreshes_total = 0
        self.resident_plane_refresh_seconds_total = 0.0
        self.admission_window_ms = 0.0
        #: startAgents opcode wall (the cold-start fleet boot measured
        #: by the agent-batching satellite; 0.0 when no agents started).
        self.agent_start_seconds = 0.0
        #: createNodes opcode wall — data staging for the node objects
        #: (plus their topology/DRA satellites). Staged in concurrent
        #: 512-wide windows like createPods; at the 1m preset the old
        #: serial awaits were a double-digit-minute pre-measurement
        #: wall the detail JSON never showed.
        self.staging_seconds = 0.0
        #: ChurnDay open-loop battery (perf/churn): the measured phase
        #: is a TIMED arrival-process window, not a drained bulk —
        #: offered vs achieved rate proves the loop stayed open,
        #: backlog growth is the saturation witness (the knee signal),
        #: and the exact attempt percentiles above are the headline.
        self.churn_offered_rate = 0.0
        self.churn_achieved_rate = 0.0
        self.churn_arrival_model = ""
        self.churn_arrivals_total = 0
        self.churn_duration_s = 0.0
        self.churn_backlog_peak = 0
        self.churn_backlog_final = 0
        self.churn_pending_final: dict[str, int] = {}
        #: None = no churn phase ran; else the is_saturated verdict.
        self.churn_saturated: bool | None = None
        #: open-loop honesty counters: arrivals fired >50ms late, and
        #: creates the transport backstop forced to serialize.
        self.churn_late_arrivals = 0
        self.churn_throttled_creates = 0
        self.churn_create_errors = 0
        self.churn_create_drain_s = 0.0
        #: fault-injection records (timeline order) + per-kind counts +
        #: the worst measured time-to-recovery.
        self.churn_faults: list[dict] = []
        self.churn_faults_injected: dict[str, int] = {}
        self.churn_recovery_seconds_max: float | None = None
        #: r20 global-assignment accounting: OCCUPIED-node fragmentation
        #: (the optimizable packing metric — the all-nodes figure above
        #: is placement-invariant once every pod places), optimal-mode
        #: solve vs greedy-degrade chunk counts over the measured phase,
        #: and the ChurnDay rebalance family's outputs — a
        #: [t_s, frag_pct, frag_occupied_pct] curve sampled through the
        #: churn window, descheduler evict-and-replace moves, and the
        #: post-churn backlog-drain recovery wall (descheduler runs
        #: only).
        self.fragmentation_occupied_pct = 0.0
        self.solver_optimal_solves_total = 0
        self.solver_optimal_fallbacks_total = 0
        self.churn_fragmentation_curve: list[list[float]] = []
        self.churn_descheduler_evictions = 0
        self.churn_rebalance_recovery_s: float | None = None
        #: Topology-slice accounting (topology/): slice-shaped gangs
        #: Permit released as one contiguous sub-mesh over the measured
        #: phase, the slice-fragmentation gauge after the last plan
        #: (free cells covered by NO feasible placement of that shape),
        #: and coordinate-plane rebuilds (reuse does not count — a
        #: stable node set should rebuild once, not per chunk).
        self.slice_gangs_bound_total = 0
        self.slice_fragmentation_pct = 0.0
        self.topology_plane_rebuilds_total = 0

    def as_dict(self) -> dict:
        import math

        def ms(v: float):
            return None if math.isnan(v) else round(v * 1e3, 3)

        return {
            "throughput_pods_per_sec": round(self.throughput, 2),
            "measured_pods": self.measured_pods,
            "measured_seconds": round(self.measured_seconds, 3),
            "attempt_p50_ms": ms(self.attempt_p50),
            "attempt_p90_ms": ms(self.attempt_p90),
            "attempt_p99_ms": ms(self.attempt_p99),
            "attempt_p999_ms": ms(self.attempt_p999),
            "attempt_percentiles_exact": self.attempt_percentiles_exact,
            "fragmentation_pct": round(self.fragmentation_pct, 2),
            "fragmentation_occupied_pct": round(
                self.fragmentation_occupied_pct, 2),
            "scheduled_total": self.scheduled_total,
            "unschedulable_total": self.unschedulable_total,
            "events_dropped_total": self.events_dropped_total,
            "events_dropped_pct": round(
                100.0 * self.events_dropped_total
                / self.events_emitted_total, 2)
            if self.events_emitted_total else 0.0,
            "host_fallback_pods": self.host_fallback_pods,
            "spread_poisoned_pods": self.spread_poisoned_pods,
            "watch_events_dispatched_total":
                self.watch_events_dispatched_total,
            "watch_predicate_checks_total":
                self.watch_predicate_checks_total,
            "watch_cache_hits_total": self.watch_cache_hits_total,
            "watch_cache_misses_total": self.watch_cache_misses_total,
            "relist_storm_agents": self.relist_storm_agents,
            "relist_storm_seconds": round(self.relist_storm_seconds, 3),
            "relist_storm_cache_hits": self.relist_storm_cache_hits,
            "relist_storm_cache_misses": self.relist_storm_cache_misses,
            "policy_evaluations_total": self.policy_evaluations_total,
            "audit_events_total": self.audit_events_total,
            "audit_events_dropped_total":
                self.audit_events_dropped_total,
            "policy_index_hits_total": self.policy_index_hits_total,
            "policy_index_residue_scans_total":
                self.policy_index_residue_scans_total,
            "policy_index_rebuilds_total":
                self.policy_index_rebuilds_total,
            "solver_solve_chunks": self.solver_solve_chunks,
            "solver_solve_seconds_total": round(
                self.solver_solve_seconds_total, 3),
            "solver_scan_width": self.solver_scan_width,
            "solver_shortlist_fallbacks_total":
                self.solver_shortlist_fallbacks_total,
            "solver_blocks_scanned_total":
                self.solver_blocks_scanned_total,
            "solver_blocks_pruned_total":
                self.solver_blocks_pruned_total,
            "solver_shortlist_hit_pct": round(
                100.0 * (1.0 - self.solver_shortlist_fallbacks_total
                         / self.solver_shortlist_pods_total), 2)
            if self.solver_shortlist_pods_total else None,
            "solver_wave_width": self.solver_wave_width,
            "solver_wave_commits_total": self.solver_wave_commits_total,
            "solver_wave_replays_total": self.solver_wave_replays_total,
            "solver_wave_replay_pct": round(
                100.0 * self.solver_wave_replays_total
                / (self.solver_wave_commits_total
                   + self.solver_wave_replays_total), 2)
            if (self.solver_wave_commits_total
                + self.solver_wave_replays_total) else None,
            "solver_pallas_solves_total": self.solver_pallas_solves_total,
            "solver_pallas_fallbacks_total":
                self.solver_pallas_fallbacks_total,
            "solve_provenance": self.solve_provenance,
            "solver_optimal_solves_total": self.solver_optimal_solves_total,
            "solver_optimal_fallbacks_total":
                self.solver_optimal_fallbacks_total,
            "prep_seconds_total": round(self.prep_seconds_total, 3),
            "plane_classes_per_chunk": self.plane_classes_per_chunk,
            "plane_bytes_uploaded_total": self.plane_bytes_uploaded_total,
            "class_split_fallback_pods": self.class_split_fallback_pods,
            "shard_count": self.shard_count,
            "shard_tensor_rebuilds_total": self.shard_tensor_rebuilds_total,
            # 6 decimals: the wavefront solve put small-chunk walls into
            # the sub-millisecond range, which 3-decimal rounding
            # reported as a (false) zero.
            "shard_solve_seconds": round(self.shard_solve_seconds, 6),
            "cross_shard_reductions_total": self.cross_shard_reductions_total,
            "process_count": self.process_count,
            "wal_appends_total": self.wal_appends_total,
            "wal_replay_entries_total": self.wal_replay_entries_total,
            "wal_fsync_seconds_total": round(
                self.wal_fsync_seconds_total, 4),
            "leader_elections_total": self.leader_elections_total,
            "serving_fast_path_pods_total": self.serving_fast_path_pods_total,
            "serving_coalesced_batches_total":
                self.serving_coalesced_batches_total,
            "resident_plane_refreshes_total":
                self.resident_plane_refreshes_total,
            "resident_plane_refresh_seconds_total": round(
                self.resident_plane_refresh_seconds_total, 4),
            "admission_window_ms": self.admission_window_ms,
            "agent_start_seconds": round(self.agent_start_seconds, 3),
            "staging_seconds": round(self.staging_seconds, 3),
            "churn_offered_rate": round(self.churn_offered_rate, 2),
            "churn_achieved_rate": round(self.churn_achieved_rate, 2),
            "churn_arrival_model": self.churn_arrival_model,
            "churn_arrivals_total": self.churn_arrivals_total,
            "churn_duration_s": round(self.churn_duration_s, 3),
            "churn_backlog_peak": self.churn_backlog_peak,
            "churn_backlog_final": self.churn_backlog_final,
            "churn_pending_final": dict(self.churn_pending_final),
            "churn_saturated": self.churn_saturated,
            "churn_late_arrivals": self.churn_late_arrivals,
            "churn_throttled_creates": self.churn_throttled_creates,
            "churn_create_errors": self.churn_create_errors,
            "churn_create_drain_s": round(self.churn_create_drain_s, 3),
            "churn_faults": list(self.churn_faults),
            "churn_faults_injected": dict(self.churn_faults_injected),
            "churn_recovery_seconds_max": self.churn_recovery_seconds_max,
            "churn_fragmentation_curve": [
                list(s) for s in self.churn_fragmentation_curve],
            "churn_descheduler_evictions": self.churn_descheduler_evictions,
            "churn_rebalance_recovery_s": self.churn_rebalance_recovery_s,
            "slice_gangs_bound_total": self.slice_gangs_bound_total,
            "slice_fragmentation_pct": round(
                self.slice_fragmentation_pct, 2),
            "topology_plane_rebuilds_total":
                self.topology_plane_rebuilds_total,
        }


DEFAULT_NODE_TEMPLATE = {
    "allocatable": {"cpu": "8", "memory": "32Gi", "pods": "110"}}
DEFAULT_POD_TEMPLATE = {
    "requests": {"cpu": "100m", "memory": "250Mi"}}


class _ServerPair:
    """The apiserver processes backing a boundary-crossing run: the HTTP
    server (policy owner) and, in wire mode, the framed-wire listener."""

    def __init__(self, api, wire):
        self.api = api
        self.wire = wire

    async def stop(self) -> None:
        if self.wire is not None:
            await self.wire.stop()
        await self.api.stop()


class _SchedulerProxy:
    """Stands in for the in-process Scheduler when scheduling happens
    in child processes (--processes >= 2): the harness keeps reading
    the same seams — queue depth, event-recorder counters, the cache
    snapshot — but the answers come from the parent's own pod informer
    (backlog = pods without a nodeName) or are structurally empty (the
    assume-cache lives in the leader replica; fragmentation over it is
    reported as 0 here and the exact attempt percentiles come over the
    measure-marker protocol instead)."""

    class _Recorder:
        emitted = 0
        dropped = 0

    class _Cache:
        @staticmethod
        def update_snapshot() -> list:
            return []

    def __init__(self):
        self.queue = self
        self.recorder = self._Recorder()
        self.cache = self._Cache()
        self._unbound: set[str] = set()

    async def setup_informers(self, factory) -> None:
        from kubernetes_tpu.client import ResourceEventHandler

        def _upd(obj):
            key = namespaced_name(obj)
            if obj.get("spec", {}).get("nodeName"):
                self._unbound.discard(key)
            else:
                self._unbound.add(key)

        factory.informer("pods").add_event_handler(ResourceEventHandler(
            on_add=_upd, on_update=lambda old, new: _upd(new),
            on_delete=lambda obj: self._unbound.discard(
                namespaced_name(obj))))

    # -- queue surface (self.queue is self) --------------------------------

    def stats(self) -> dict:
        return {"active": len(self._unbound), "backoff": 0,
                "unschedulable": 0, "gated": 0, "in_flight": 0}

    def backlog_depth(self) -> int:
        return len(self._unbound)

    # -- lifecycle ---------------------------------------------------------

    async def run(self, batch_size: int = 1) -> None:
        # The replicas schedule; the proxy just holds the task slot the
        # harness cancels on teardown.
        await asyncio.Event().wait()

    async def stop(self) -> None:
        pass


class PerfRunner:
    """Executes one workload (template ops + params) against an in-process
    store + scheduler, mirroring mustSetupCluster → runWorkload."""

    def __init__(self, backend=None, batch_size: int = 1,
                 scheduler_kwargs: Mapping | None = None,
                 scheduler_config: Mapping | None = None,
                 through_apiserver: bool = False,
                 profile_dir: str | None = None,
                 policy_count: int = 0,
                 policy_tenants: int = 0,
                 audit_rules: list | None = None,
                 shards: int | None = None,
                 processes: int | None = None,
                 data_dir: str | None = None):
        self.backend = backend
        self.batch_size = batch_size
        self.scheduler_kwargs = dict(scheduler_kwargs or {})
        #: control-plane shard count for the backing store (>1 builds a
        #: ShardedNodeStore; None resolves KTPU_SHARDS, default 1).
        self.shards = shards
        #: OS-process count for the control plane (r22 tentpole). >1
        #: spawns one apiserver process per shard plus a leader-elected
        #: scheduler pair and drives them through the cross-process
        #: facade; None resolves KTPU_PROCESSES; <=1 builds today's
        #: in-process tree exactly (nothing multiproc is constructed).
        self.processes = processes
        #: KTPU_DATA_DIR override for the shard processes (per-shard
        #: snapshot + WAL directories live under it).
        self.data_dir = data_dir
        #: measure-marker protocol client, live only during a
        #: multi-process run (see multiproc/controlplane.py).
        self._mp = None
        self._cp = None
        #: ValidatingAdmissionPolicies (+bindings) installed before the
        #: run — the policy-chain overhead knob (BASELINE r9: headline
        #: with a 10-policy set vs disabled). Only meaningful with
        #: through_apiserver (the policy chain lives on the servers).
        self.policy_count = policy_count
        #: >0 shards the policy set across N tenant namespaces with
        #: per-namespace selectors and disjoint resourceRules so only
        #: ~1% of stored policies match any given request — the
        #: realistic multi-tenant shape the O(matching) index targets
        #: (the 1k-policy headline row uses 1000/100). 0 keeps the
        #: legacy uniform all-matching set (the r9 comparison row).
        self.policy_tenants = policy_tenants
        #: audit policy rules for the run's AuditPipeline ([] = level
        #: None for everything: stage events cost nothing).
        self.audit_rules = list(audit_rules or [])
        self._policy_engine = None
        self._audit = None
        #: Optional inline KubeSchedulerConfiguration (a workload family may
        #: enable non-default plugins, e.g. NodeResourceTopologyMatch).
        self.scheduler_config = scheduler_config
        #: Cross the process boundary like the reference's scheduler_perf
        #: (in-process apiserver + REAL wire): all traffic — workload
        #: writes, the scheduler's informers, and binding POSTs — goes over
        #: the apiserver instead of direct store calls. True/"http" = the
        #: HTTP/1.1+JSON wire; "wire" = the KTPU multiplexed framed wire
        #: (the HTTP/2 analog core components use — apiserver/wire.py).
        self.through_apiserver = through_apiserver
        #: jax.profiler trace of the MEASURED phase only (not warmup/jit
        #: compile) when the backend supports it.
        self.profile_dir = profile_dir

    async def run(self, template_ops: list, params: Mapping[str, Any],
                  timeout: float = 600.0) -> WorkloadResult:
        from kubernetes_tpu.utils import flags
        if self.shards is None:
            return await self._run_inner(template_ops, params, timeout)
        # The host prep's per-shard accounting resolves the same
        # flagless policy (control_plane_shards); an explicit shard
        # request must reach it too — scoped to this run (save/restore
        # so overlapping runs can't cross-restore each other's value).
        with flags.scoped_set("KTPU_SHARDS", self.shards):
            return await self._run_inner(template_ops, params, timeout)

    async def _run_inner(self, template_ops: list,
                         params: Mapping[str, Any],
                         timeout: float = 600.0) -> WorkloadResult:
        from kubernetes_tpu.utils import flags
        nproc = self.processes
        if nproc is None:
            nproc = int(flags.get("KTPU_PROCESSES") or 1)
        cp = None
        self._mp = None
        self._cp = None
        server = None
        client = None
        if int(nproc) > 1:
            # r22 tentpole topology: one apiserver OS process per shard
            # plus a leader-elected scheduler pair; the parent only
            # stages the workload and reads results through the
            # cross-process facade. N<=1 takes the else branch and
            # builds today's in-process tree exactly as before.
            from kubernetes_tpu.multiproc import (
                MeasureProtocol,
                MultiProcessControlPlane,
            )
            backend_spec = None
            if self.backend is not None:
                backend_spec = {"kind": "tpu", "chunk": int(getattr(
                    self.backend, "max_batch", 1) or 1)}
            cp = MultiProcessControlPlane(
                int(nproc),
                data_dir=self.data_dir or flags.get("KTPU_DATA_DIR"),
                backend_spec=backend_spec, batch_size=self.batch_size,
                scheduler_kwargs=self.scheduler_kwargs)
            try:
                await cp.start()
                await cp.start_schedulers(2)
                store = backing = cp.client()
                metrics = SchedulerMetrics()
                sched = _SchedulerProxy()
                factory = InformerFactory(store)
                await sched.setup_informers(factory)
                self._mp = MeasureProtocol(store)
                self._cp = cp
            except BaseException:
                await cp.stop()
                raise
            return await self._drive(template_ops, params, timeout,
                                     backing, store, metrics, sched,
                                     factory, server, client, cp)
        backing = new_cluster_store(shards=self.shards)
        install_core_validation(backing)
        try:
            api_kw = {}
            if self.through_apiserver:
                # The policy chain rides the servers: admission
                # (webhooks + expression policies) and the audit
                # pipeline are ALWAYS constructed for boundary-crossing
                # runs, so the detail JSON's policy/audit counters are
                # real measurements (zero when no policies/rules exist).
                from kubernetes_tpu.apiserver.admission import (
                    WebhookAdmission,
                )
                from kubernetes_tpu.policy import (
                    AuditPipeline,
                    AuditPolicy,
                    PolicyEngine,
                )
                self._policy_engine = PolicyEngine(backing)
                self._audit = AuditPipeline(
                    AuditPolicy(self.audit_rules))
                api_kw = {"admission": WebhookAdmission(
                    backing, policy_engine=self._policy_engine),
                    "audit": self._audit}
                await self._install_policies(backing)
            if self.through_apiserver == "wire":
                # The core-component transport: HTTP server up (policy
                # lives there), store traffic over the multiplexed wire.
                from kubernetes_tpu.apiserver.server import APIServer
                from kubernetes_tpu.apiserver.wire import (
                    WireServer,
                    WireStore,
                )
                server = _ServerPair(APIServer(backing, **api_kw), None)
                await server.api.start()
                server.wire = WireServer.for_apiserver(
                    server.api, host="unix:")
                await server.wire.start()
                client = WireStore(server.wire.target)
                store = client
            elif self.through_apiserver:
                from kubernetes_tpu.apiserver.client import RemoteStore
                from kubernetes_tpu.apiserver.server import APIServer
                server = _ServerPair(APIServer(backing, **api_kw), None)
                await server.api.start()
                client = RemoteStore(server.api.url)
                store = client
            else:
                store = backing
            metrics = SchedulerMetrics()
            profiles = None
            if self.scheduler_config is not None:
                from kubernetes_tpu.config.scheduler import load_config
                cfg = load_config(self.scheduler_config)
                profiles = {p.scheduler_name: p.build_framework(
                    store=store, metrics=metrics) for p in cfg.profiles}
            sched = Scheduler(store, seed=42, backend=self.backend,
                              metrics=metrics, profiles=profiles,
                              **self.scheduler_kwargs)
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
        except BaseException:
            # Setup failed after the server/client came up — don't leak
            # the bound socket or background tasks.
            if client is not None:
                await client.close()
            if server is not None:
                await server.stop()
            backing.stop()
            raise
        return await self._drive(template_ops, params, timeout, backing,
                                 store, metrics, sched, factory, server,
                                 client, None)

    async def _drive(self, template_ops: list, params: Mapping[str, Any],
                     timeout: float, backing, store, metrics, sched,
                     factory, server, client, cp) -> WorkloadResult:
        """The opcode loop, shared by both construction paths (`cp` is
        the MultiProcessControlPlane for --processes >= 2, else None)."""
        # Bound-pod accounting via watch events, not store LISTs: a LIST
        # deep-copies every object and was the harness's own hot spot.
        bound_keys: set[str] = set()

        def _track(obj):
            if obj.get("spec", {}).get("nodeName"):
                bound_keys.add(namespaced_name(obj))

        from kubernetes_tpu.client import ResourceEventHandler
        factory.informer("pods").add_event_handler(ResourceEventHandler(
            on_add=_track, on_update=lambda old, new: _track(new),
            on_delete=lambda obj: bound_keys.discard(namespaced_name(obj))))

        factory.start()
        await factory.wait_for_sync()
        run_task = asyncio.ensure_future(sched.run(batch_size=self.batch_size))

        result = WorkloadResult()
        node_count = 0
        pod_seq = 0
        created_total = 0
        agents: list = []
        agent_dir: str | None = None
        deadline = time.monotonic() + timeout
        try:
            for op in template_ops:
                opcode = op["opcode"]
                if opcode == "startAgents":
                    # Agent-backed staging: N hollow-kubelet NodeAgents
                    # (kubernetes_tpu/agent) register their own Nodes and
                    # consume field-selector-filtered pod watches — the
                    # kubelet topology — instead of createNodes' bare
                    # data staging. Their mark-Running writes and lease
                    # renewals ride the same store/wire as the workload.
                    import tempfile

                    from kubernetes_tpu.agent import NodeAgent
                    count = _resolve_count(op, params)
                    tmpl = {**DEFAULT_NODE_TEMPLATE,
                            **(op.get("nodeTemplate") or {})}
                    if agent_dir is None:
                        agent_dir = tempfile.mkdtemp(prefix="ktpu-agents-")
                    new_agents = [
                        NodeAgent(store, f"node-{node_count + i}",
                                  checkpoint_dir=agent_dir,
                                  node_template=copy.deepcopy(tmpl),
                                  lease_period=float(_subst(
                                      op.get("leasePeriod", 5.0),
                                      params)))
                        for i in range(count)]
                    # Track BEFORE starting so a mid-boot failure still
                    # stops every booted agent in the finally block
                    # (stop() on a never-started agent is a no-op).
                    # Batched fleet boot (NodeAgent.start_many): wide
                    # registration windows first, then wide watch
                    # establishment — per-agent serialized handshakes
                    # were the r12-identified 50k-agent headroom.
                    agents.extend(new_agents)
                    t0 = time.monotonic()
                    from kubernetes_tpu.agent.agent import NodeAgent as _NA
                    await _NA.start_many(new_agents)
                    result.agent_start_seconds += time.monotonic() - t0
                    node_count += count

                elif opcode == "createNodes":
                    count = _resolve_count(op, params)
                    tmpl = {**DEFAULT_NODE_TEMPLATE,
                            **(op.get("nodeTemplate") or {})}
                    # Optional NUMA topology (BASELINE config #4): create a
                    # NodeResourceTopology per node, splitting allocatable
                    # across zones the way a device-manager agent reports.
                    topo = op.get("topologyTemplate")
                    # Optional DRA inventory (SURVEY §2.3 dynamicresources):
                    # one ResourceSlice per node listing devices with NUMA
                    # attributes, plus the DeviceClass selecting them.
                    dra = op.get("draTemplate")
                    t0 = time.monotonic()
                    if dra:
                        from kubernetes_tpu.api.types import (
                            make_device_class,
                            make_resource_slice,
                        )
                        cls = dra.get("className", "tpu")
                        try:
                            await store.create(
                                "deviceclasses",
                                make_device_class(cls, {"type": cls}))
                        except Exception:
                            pass  # already created by an earlier op

                    # Staging writes go out in concurrent 512-wide
                    # windows, same shape as createPods: each window
                    # coalesces into one multiplexed wire frame, where
                    # per-node serial awaits paid a full RTT apiece —
                    # at the 1m preset that serial loop alone was a
                    # double-digit-minute wall before any measurement.
                    async def stage_node(i):
                        name = f"node-{node_count + i}"
                        await store.create("nodes", make_node(
                            name, **copy.deepcopy(tmpl)))
                        if topo:
                            await store.create(
                                "noderesourcetopologies",
                                split_node_topology(
                                    name, tmpl.get("allocatable") or {},
                                    num_zones=int(topo.get("zones", 2)),
                                    devices=topo.get("devices")))
                        if dra:
                            zones = int(dra.get("zones", 2))
                            per = int(dra.get("devicesPerZone", 4))
                            devices = [
                                {"name": f"dev-{z}-{k}",
                                 "attributes": {"type": cls,
                                                "numa": str(z)}}
                                for z in range(zones) for k in range(per)]
                            await store.create(
                                "resourceslices",
                                make_resource_slice(
                                    name, dra.get("driver", "dra.ktpu"),
                                    devices))

                    for lo in range(0, count, 512):
                        await asyncio.gather(*(
                            stage_node(i)
                            for i in range(lo, min(lo + 512, count))))
                    result.staging_seconds += time.monotonic() - t0
                    node_count += count

                elif opcode == "createPods":
                    count = _resolve_count(op, params)
                    tmpl = {**DEFAULT_POD_TEMPLATE,
                            **(op.get("podTemplate") or {})}
                    # DRA pods: podTemplate.claim stamps one ResourceClaim
                    # per pod (the resourceclaim controller's output shape)
                    # referenced via spec.resourceClaims.
                    claim_tmpl = tmpl.pop("claim", None)
                    measured = bool(op.get("collectMetrics"))
                    if measured:
                        # Metric window starts now: percentiles and
                        # throughput cover only the measured phase (warmup
                        # attempts — including jit compile — are excluded).
                        window = self._begin_measure(metrics, backing)
                        await self._mp_begin()
                        if self.profile_dir and hasattr(
                                self.backend, "start_profile"):
                            self.backend.start_profile(self.profile_dir)
                    names = [f"pod-{pod_seq + i}" for i in range(count)]
                    # Writes go out in concurrent windows (the reference
                    # harness drives the apiserver with multi-goroutine
                    # client QPS; serial awaits would make the HTTP
                    # boundary the benchmark). 512-wide windows let the
                    # wire transport coalesce a whole window into one
                    # multiplexed frame.
                    if claim_tmpl:
                        from kubernetes_tpu.api.types import (
                            make_resource_claim,
                        )

                        async def create_claimed(name):
                            await store.create(
                                "resourceclaims", make_resource_claim(
                                    f"{name}-c0",
                                    requests=copy.deepcopy(
                                        claim_tmpl.get("requests") or []),
                                    constraints=copy.deepcopy(
                                        claim_tmpl.get("constraints")
                                        or [])))
                            await store.create("pods", make_pod(
                                name, resource_claims=[{
                                    "name": "c0",
                                    "resourceClaimName": f"{name}-c0"}],
                                **copy.deepcopy(tmpl)))

                        for lo in range(0, count, 512):
                            await asyncio.gather(*(
                                create_claimed(name)
                                for name in names[lo:lo + 512]))
                    else:
                        for lo in range(0, count, 512):
                            await asyncio.gather(*(
                                store.create("pods", make_pod(
                                    name, **copy.deepcopy(tmpl)))
                                for name in names[lo:lo + 512]))
                    pod_seq += count
                    created_total += count
                    if op.get("scopedBarrier") and not measured:
                        # Wait for THIS op's pods only (reference barriers
                        # take a labelSelector): lets a warmup op complete
                        # even when it deletes other pods (a preemption
                        # warmup shrinks the global bound count, so a
                        # global barrier would never pass).
                        pod_ns = tmpl.get("namespace", "default")
                        want = {f"{pod_ns}/{n}" for n in names}
                        await self._wait_keys(bound_keys, want, deadline)
                    if measured:
                        # Scoped to THIS op's pods (reference barriers take
                        # a labelSelector for the same reason): preemption
                        # deletes victims, so the global count can shrink.
                        pod_ns = tmpl.get("namespace", "default")
                        want = {f"{pod_ns}/{n}" for n in names}
                        await self._wait_keys(bound_keys, want, deadline)
                        self._end_measure(result, metrics, backing,
                                          window, count)
                        await self._mp_end(result)
                        if self.profile_dir and hasattr(
                                self.backend, "stop_profile"):
                            self.backend.stop_profile()

                elif opcode == "ungatePods":
                    # Strip schedulingGates from every gated pod (the
                    # reference's gated-pods workload: a controller lifts
                    # the gate; PreEnqueue re-admits). Measured variant
                    # times gate-removal → all bound.
                    measured = bool(op.get("collectMetrics"))
                    if measured:
                        window = self._begin_measure(metrics, backing)
                        await self._mp_begin()
                    gated = [p for p in (await store.list("pods")).items
                             if p["spec"].get("schedulingGates")]

                    def strip(obj):
                        obj["spec"].pop("schedulingGates", None)
                        return obj
                    for p in gated:
                        await store.guaranteed_update(
                            "pods", namespaced_name(p), strip)
                    if measured:
                        await self._wait_bound(bound_keys, created_total,
                                               deadline)
                        self._end_measure(result, metrics, backing,
                                          window, len(gated))
                        await self._mp_end(result)

                elif opcode == "relistStorm":
                    # Every agent reconnects AT ONCE: tear down its
                    # watch, full LIST, re-watch (agent.force_relist) —
                    # the cold-start storm ROADMAP #2 names. With the
                    # watch cache active the N LISTs are reads of one
                    # shared snapshot (hit/miss deltas recorded); the
                    # direct-mvcc path pays N table scans.
                    h0, m0 = self._cache_totals(backing)
                    t0 = time.monotonic()
                    await asyncio.gather(
                        *(a.force_relist() for a in agents))
                    result.relist_storm_seconds = time.monotonic() - t0
                    result.relist_storm_agents = len(agents)
                    h1, m1 = self._cache_totals(backing)
                    result.relist_storm_cache_hits = int(h1 - h0)
                    result.relist_storm_cache_misses = int(m1 - m0)

                elif opcode == "churnOpenLoop":
                    # ChurnDay (perf/churn): a TIMED open-loop arrival
                    # window — pods enqueue at the process's rate on an
                    # absolute clock whatever the scheduler does, with
                    # an optional deterministic fault timeline injected
                    # mid-wave. No trailing barrier belongs after this
                    # op: a saturated run deliberately ends with unbound
                    # pods (that backlog IS the measurement).
                    created_total += await self._run_churn_phase(
                        op, params, result, metrics, backing, store,
                        sched, factory, agents, bound_keys, pod_seq)
                    pod_seq += result.churn_arrivals_total

                elif opcode == "barrier":
                    await self._wait_bound(bound_keys, created_total, deadline)

                elif opcode == "sleep":
                    await asyncio.sleep(float(
                        _subst(op.get("duration", 0), params)))

                elif opcode == "churn":
                    # Delete + recreate a slice of bound pods: queue pressure
                    # and cache-update load (reference churnOp).
                    count = _resolve_count(op, params)
                    pods = (await store.list("pods")).items[:count]
                    for p in pods:
                        await store.delete("pods", namespaced_name(p))
                    created_total -= len(pods)
                    # Wait for the deletions to reach the informer before
                    # recreating, or the next barrier reads stale bound keys.
                    while len(bound_keys) > created_total \
                            and time.monotonic() < deadline:
                        await asyncio.sleep(0.01)
                    tmpl = {**DEFAULT_POD_TEMPLATE,
                            **(op.get("podTemplate") or {})}
                    for i in range(len(pods)):
                        await store.create("pods", make_pod(
                            f"pod-{pod_seq + i}", **copy.deepcopy(tmpl)))
                    pod_seq += len(pods)
                    created_total += len(pods)

                else:
                    raise ValueError(f"unknown opcode {opcode!r}")
        finally:
            if agents:
                await asyncio.gather(
                    *(a.stop() for a in agents), return_exceptions=True)
            if agent_dir is not None:
                import shutil
                shutil.rmtree(agent_dir, ignore_errors=True)
            await sched.stop()
            run_task.cancel()
            factory.stop()
            if cp is not None:
                # WAL/HA counters live in the children: pull them while
                # the shard sockets still answer (best-effort on an
                # exception path — the primary failure must surface).
                try:
                    await self._finalize_multiproc(result, backing)
                except Exception:
                    pass
            if client is not None:
                await client.close()
            if server is not None:
                await server.stop()
            backing.stop()
            if cp is not None:
                await cp.stop()
                self._cp = None
                self._mp = None

        # Percentiles were captured over the measured window above
        # (scheduler_scheduling_attempt_duration_seconds — SURVEY §5.5);
        # fall back to whole-run percentiles when no phase was measured.
        if cp is None:
            if result.measured_pods == 0:
                h = metrics.attempt_duration
                labels = {"result": "scheduled",
                          "profile": "default-scheduler"}
                result.attempt_p50 = h.percentile(0.50, **labels)
                result.attempt_p90 = h.percentile(0.90, **labels)
                result.attempt_p99 = h.percentile(0.99, **labels)
            result.scheduled_total = _result_count(metrics, "scheduled")
            result.unschedulable_total = _result_count(
                metrics, "unschedulable")
        result.shard_count = int(getattr(backing, "node_shards", 1))
        result.fragmentation_pct = self._fragmentation(sched)
        result.fragmentation_occupied_pct = \
            self._fragmentation_occupied(sched)
        metrics.fragmentation_pct.set(result.fragmentation_occupied_pct)
        result.events_emitted_total = sched.recorder.emitted
        result.events_dropped_total = sched.recorder.dropped
        return result

    async def _run_churn_phase(self, op: Mapping, params: Mapping[str, Any],
                               result: WorkloadResult, metrics, backing,
                               store, sched, factory, agents: list,
                               bound_keys: set, pod_seq: int) -> int:
        """Execute one churnOpenLoop op; returns the net pod-count delta
        (arrivals + fault creates − fault deletes) for created_total."""
        from kubernetes_tpu.metrics.registry import ChurnMetrics
        from kubernetes_tpu.perf.churn import (
            ChurnDriver,
            FaultInjector,
            build_fault_timeline,
            is_saturated,
            make_arrival_process,
        )
        duration = float(_subst(op.get("duration", 5.0), params))
        seed = int(_subst(op.get("seed", 0), params))
        arrival = {k: _subst(v, params)
                   for k, v in (op.get("arrival")
                                or {"model": "poisson", "rate": 100}).items()}
        process = make_arrival_process(arrival, seed=seed)
        churn_metrics = ChurnMetrics(metrics.registry)
        measured = bool(op.get("collectMetrics"))
        tmpl = {**DEFAULT_POD_TEMPLATE, **(op.get("podTemplate") or {})}
        pod_ns = tmpl.get("namespace", "default")

        async def create_arrival(name: str, template: dict | None = None):
            await store.create("pods", make_pod(
                name, **(template if template is not None
                         else copy.deepcopy(tmpl))))

        driver = ChurnDriver(
            process, duration,
            create_pod=create_arrival,
            backlog_stats=sched.queue.stats,
            # Keep scheduler_pending_pods{queue} fresh under saturation
            # (the scheduler only refreshes it per popped batch).
            on_backlog=metrics.set_pending,
            metrics=churn_metrics,
            name_prefix=f"churn{pod_seq}")

        injector = None
        timeline = []
        nlc = None
        fault_specs = op.get("faults") or []
        if fault_specs:
            timeline = build_fault_timeline(
                [{k: _subst(v, params) for k, v in f.items()}
                 for f in fault_specs],
                seed=seed,
                node_names=[a.node_name for a in agents])
            injector = FaultInjector(
                store=store, agents=agents, bound_keys=bound_keys,
                create_pod=create_arrival,
                backlog_fn=sched.queue.backlog_depth,
                control_plane=self._cp,
                metrics=churn_metrics, pod_template=tmpl,
                recovery_threshold=int(_subst(
                    op.get("recoveryThreshold", 10), params)),
                recovery_timeout=float(_subst(
                    op.get("recoveryTimeout", 60.0), params)),
                namespace=pod_ns)
            if any(ev.kind == "nodeDeath" for ev in timeline):
                # Node death needs the lease-expiry machinery live: a
                # killed agent's Lease goes stale, the controller
                # taints unreachable after the grace period, and the
                # NoExecute manager evicts (SURVEY §5.3).
                from kubernetes_tpu.controllers.nodelifecycle import (
                    NodeLifecycleController,
                )
                tol = float(_subst(op.get("tolerationSeconds", 0.25),
                                   params))
                nlc = NodeLifecycleController(
                    store,
                    node_monitor_period=0.1,
                    node_monitor_grace_period=float(_subst(
                        op.get("nodeGracePeriod", 1.0), params)),
                    default_toleration_seconds=tol,
                    # The admission default stamps 300s on every pod;
                    # the scenario's toleration knob caps it so the
                    # eviction clock runs at bench speed.
                    toleration_seconds_cap=tol)
                nlc.setup(factory)
                factory.informer("leases").start()
                await factory.informer("leases").wait_for_sync()
                nlc.start()

        # Rebalance family (r20): an optional descheduler closes the
        # consolidation loop DURING the churn window, and a fragmentation
        # sampler records the over-time curve the on/off pair compares.
        # `descheduler: {enabled, period, budget, threshold}` on the op
        # pins it per workload; absent, the KTPU_DESCHEDULER flag rules.
        desch = None
        dcfg = op.get("descheduler")
        if dcfg is None:
            from kubernetes_tpu.utils import flags as _flags
            d_on = bool(_flags.get("KTPU_DESCHEDULER"))
            dcfg = {}
        else:
            dcfg = {k: _subst(v, params) for k, v in dcfg.items()}
            d_on = bool(dcfg.get("enabled", True))
        if d_on:
            from kubernetes_tpu.controllers.descheduler import (
                DeschedulerController,
            )
            desch = DeschedulerController(
                store,
                period=float(dcfg.get("period", 0.25)),
                budget=int(dcfg["budget"]) if "budget" in dcfg else None,
                threshold=float(dcfg.get("threshold", 0.5)))
            desch.setup(factory)
            for res in ("pods", "nodes"):
                factory.informer(res).start()
                await factory.informer(res).wait_for_sync()
            desch.start()

        curve: list[list[float]] = []
        sample_every = float(_subst(op.get("sampleInterval", 0.0), params))

        async def _sample(t0: float) -> None:
            while True:
                curve.append([
                    round(time.monotonic() - t0, 3),
                    round(self._fragmentation(sched), 2),
                    round(self._fragmentation_occupied(sched), 2)])
                await asyncio.sleep(sample_every)

        window = self._begin_measure(metrics, backing) if measured else None
        if measured:
            await self._mp_begin()
        sampler = None
        try:
            t0 = time.monotonic()
            if sample_every > 0:
                sampler = asyncio.ensure_future(_sample(t0))
            inj_task = None
            if injector is not None:
                inj_task = asyncio.ensure_future(
                    injector.run(timeline, t0))
            phase = await driver.run(t0)
            if inj_task is not None:
                await inj_task
                await injector.drain()
            if desch is not None:
                # Recovery: stop proposing moves, then the bounded wait
                # for the backlog (evicted replacements included) to
                # drain back under the threshold.
                await desch.stop()
                r0 = time.monotonic()
                r_deadline = r0 + float(_subst(
                    op.get("recoveryTimeout", 30.0), params))
                thresh = int(_subst(op.get("recoveryThreshold", 10),
                                    params))
                while time.monotonic() < r_deadline \
                        and sched.queue.backlog_depth() > thresh:
                    await asyncio.sleep(0.05)
                result.churn_rebalance_recovery_s = round(
                    time.monotonic() - r0, 3)
        finally:
            if sampler is not None:
                sampler.cancel()
                # one last point so the curve shows the recovered state
                curve.append([
                    round(time.monotonic() - t0, 3),
                    round(self._fragmentation(sched), 2),
                    round(self._fragmentation_occupied(sched), 2)])
            if desch is not None:
                if not desch._stopped:
                    await desch.stop()
                result.churn_descheduler_evictions = desch.evictions
            if nlc is not None:
                await nlc.stop()
        result.churn_fragmentation_curve = curve
        if measured:
            self._end_measure(result, metrics, backing, window,
                              phase.arrivals_total)
            await self._mp_end(result)
        result.churn_offered_rate = phase.offered_rate
        result.churn_achieved_rate = phase.achieved_rate
        result.churn_arrival_model = phase.arrival_model
        result.churn_arrivals_total = phase.arrivals_total
        result.churn_duration_s = phase.duration
        result.churn_backlog_peak = phase.backlog_peak
        result.churn_backlog_final = phase.backlog_final
        result.churn_pending_final = dict(phase.pending_final)
        result.churn_saturated = is_saturated(
            phase.arrivals_total, phase.backlog_final,
            float(_subst(op.get("saturationFrac", 0.2), params)),
            offered_rate=phase.offered_rate,
            achieved_rate=phase.achieved_rate)
        result.churn_late_arrivals = phase.late_arrivals
        result.churn_throttled_creates = phase.throttled_creates
        result.churn_create_errors = phase.create_errors
        result.churn_create_drain_s = phase.create_drain_s
        net = phase.arrivals_total
        if injector is not None:
            result.churn_faults = list(injector.results)
            counts: dict[str, int] = {}
            for rec in injector.results:
                counts[rec["kind"]] = counts.get(rec["kind"], 0) + 1
            result.churn_faults_injected = counts
            recoveries = [rec["recovery_s"] for rec in injector.results
                          if rec.get("recovery_s") is not None]
            if recoveries:
                result.churn_recovery_seconds_max = max(recoveries)
            net += injector.net_created
        return net

    async def _install_policies(self, backing) -> None:
        """The overhead knob: N pass-through pod policies + bindings
        (BASELINE r9 measures the headline with 10 vs 0). With
        policy_tenants > 0 the set is tenant-sharded instead —
        realistic multi-tenant matching for the O(matching) index."""
        if not self.policy_count:
            return
        if self.policy_tenants:
            await self._install_tenant_policies(backing)
            return
        from kubernetes_tpu.api.types import (
            make_validating_admission_policy,
            make_vap_binding,
        )
        for i in range(self.policy_count):
            name = f"bench-policy-{i}"
            await backing.create(
                "validatingadmissionpolicies",
                make_validating_admission_policy(name, [
                    {"expression": "size(object.spec.containers) >= 1"
                                   " and not has(object.spec.paused)",
                     "message": "bench policy"}],
                    match_constraints={"resourceRules": [
                        {"resources": ["pods"],
                         "operations": ["CREATE"]}]}))
            await backing.create("validatingadmissionpolicybindings",
                                 make_vap_binding(f"{name}-b", name))

    async def _install_tenant_policies(self, backing) -> None:
        """Realistic tenant shards (ISSUE 15 headline shape): N policies
        across T tenant namespaces — 4 of 5 are pod-CREATE policies
        scoped by a per-tenant namespaceSelector (the bench's pods land
        in "default", labeled tenant t0, so only ~N·0.8/T of them
        match: ~1% at 1000/100), 1 of 5 carries disjoint non-pod
        resourceRules the exact-key index never surfaces for a pod
        create. A ~1% slice of pod policies (stride 97, coprime with
        the tenant stride so breadth never correlates with one tenant's
        whole shard) adds a matchConditions prefilter + a variables
        entry (the breadth surface rides the measured path) and a
        second, paramRef-carrying binding against a shared per-tenant
        ConfigMap (prebuilt param closures exercised)."""
        from kubernetes_tpu.api.types import (
            make_config_map,
            make_namespace,
            make_validating_admission_policy,
            make_vap_binding,
        )
        from kubernetes_tpu.store.mvcc import AlreadyExists
        tenants = self.policy_tenants
        other_rules = ["configmaps", "secrets", "services",
                       "deployments", "leases", "replicasets",
                       "statefulsets", "daemonsets"]
        for t in range(tenants):
            ns = make_namespace(f"tenant-{t}")
            ns["metadata"]["labels"] = {"ktpu.io/tenant": f"t{t}"}
            await backing.create("namespaces", ns)
        # The measured pods ride the "default" namespace: label it as
        # tenant t0 so exactly that tenant's shard applies.
        default_ns = make_namespace("default")
        default_ns["metadata"]["labels"] = {"ktpu.io/tenant": "t0"}
        try:
            await backing.create("namespaces", default_ns)
        except AlreadyExists:
            cur = await backing.get("namespaces", "default")
            cur.setdefault("metadata", {})["labels"] = {
                "ktpu.io/tenant": "t0"}
            await backing.update("namespaces", cur)
        for t in range(tenants):
            await backing.create(
                "configmaps",
                make_config_map(f"tenant-caps-{t}",
                                data={"maxPriority": "1000000"}))
        for i in range(self.policy_count):
            t = i % tenants
            name = f"tenant-policy-{i}"
            if i % 5 == 4:
                # Disjoint non-pod rules: a pod CREATE never surfaces
                # these from the exact-key index (and the linear scan
                # pays for skipping them — the comparison's point).
                constraints = {"resourceRules": [
                    {"resources": [other_rules[i % len(other_rules)]],
                     "operations": ["CREATE", "UPDATE"]}]}
            else:
                constraints = {
                    "resourceRules": [{"resources": ["pods"],
                                       "operations": ["CREATE"]}],
                    "namespaceSelector": {
                        "matchLabels": {"ktpu.io/tenant": f"t{t}"}},
                }
            kwargs = {}
            validations = [
                {"expression": "size(object.spec.containers) >= 1"
                               " and not has(object.spec.paused)",
                 "message": f"tenant t{t} policy"}]
            spec_extra = {}
            if i % 97 == 0 and i % 5 != 4:
                spec_extra = {
                    "matchConditions": [
                        {"name": "has-spec",
                         "expression": "has(object.spec)"}],
                    "variables": [
                        {"name": "cset",
                         "expression": "object.spec.containers"}],
                }
                validations = [
                    {"expression": "size(variables.cset) >= 1",
                     "message": f"tenant t{t} policy"}]
                kwargs["param_kind"] = "ConfigMap"
            policy = make_validating_admission_policy(
                name, validations, match_constraints=constraints,
                **kwargs)
            policy["spec"].update(spec_extra)
            await backing.create("validatingadmissionpolicies", policy)
            await backing.create("validatingadmissionpolicybindings",
                                 make_vap_binding(f"{name}-b", name))
            if kwargs:
                await backing.create(
                    "validatingadmissionpolicybindings",
                    make_vap_binding(f"{name}-pb", name, param_ref={
                        "name": f"tenant-caps-{t}",
                        "namespace": "default"}))

    def _policy_totals(self) -> tuple[float, ...]:
        """(evals, index hits, residue scans, rebuilds, audit events,
        audit drops) — the policy/audit counter snapshot the measured
        window differences."""
        evals = hits = residue = rebuilds = audits = dropped = 0.0
        if self._policy_engine is not None:
            eng = self._policy_engine
            evals = sum(eng.evaluations._values.values())
            hits = eng.index_hits.value()
            residue = eng.index_residue_scans.value()
            rebuilds = eng.index_rebuilds.value()
        if self._audit is not None:
            audits = sum(
                self._audit.sink.events_total._values.values())
            dropped = self._audit.sink.events_dropped.value()
        return evals, hits, residue, rebuilds, audits, dropped

    @staticmethod
    def _cache_totals(backing) -> tuple[float, float]:
        """(hits, misses) of the store's watch-cache tier (0s when the
        KTPU_WATCH_CACHE=0 kill switch disabled it)."""
        cacher = getattr(backing, "cacher", None)
        if cacher is None:
            return 0.0, 0.0
        return cacher.metrics.hits.value(), cacher.metrics.misses.value()

    async def _mp_begin(self) -> None:
        """Open the child-side measured window (multi-process runs
        only): the leader marks its exact attempt recorder."""
        if self._mp is not None:
            await self._mp.begin()

    async def _mp_end(self, result: WorkloadResult) -> None:
        """Close the child-side window: the leader's exact attempt
        percentiles override the parent's recorder (which never saw an
        attempt — scheduling happened in another process). A failover
        mid-window can eat the marker; the parent-side wall-clock
        throughput from _end_measure then stands alone."""
        if self._mp is None:
            return
        row = await self._mp.end()
        import math
        try:
            pcts = {q: float(row[k]) for q, k in (
                (0.50, "p50"), (0.90, "p90"),
                (0.99, "p99"), (0.999, "p999"))}
        except (KeyError, TypeError, ValueError):
            return
        if math.isnan(pcts[0.50]):
            return
        result.attempt_p50 = pcts[0.50]
        result.attempt_p90 = pcts[0.90]
        result.attempt_p99 = pcts[0.99]
        result.attempt_p999 = pcts[0.999]
        result.attempt_percentiles_exact = True

    async def _finalize_multiproc(self, result: WorkloadResult,
                                  backing) -> None:
        """Pull the run's child-process counters (leader status row +
        per-shard WAL stats) — must run BEFORE the control plane stops:
        the sums live in the children, not the parent."""

        def _i(v) -> int:
            try:
                return int(v)
            except (TypeError, ValueError):
                return 0

        row = await self._mp.status()
        result.process_count = int(backing.node_shards)
        result.scheduled_total = _i(row.get("scheduledTotal"))
        result.leader_elections_total = _i(row.get("elections"))
        total = (await backing.control_stats()).get("total") or {}
        result.wal_appends_total = _i(total.get("walAppends"))
        result.wal_replay_entries_total = _i(total.get("walReplayed"))
        result.wal_fsync_seconds_total = float(
            total.get("walFsyncSeconds") or 0.0)

    def _begin_measure(self, metrics: SchedulerMetrics, backing) -> tuple:
        deg = metrics.backend_degradations
        wm = backing.watch_metrics
        return (metrics.attempt_duration.snapshot(
            result="scheduled", profile="default-scheduler"),
            time.monotonic(),
            deg.value(kind="host_fallback"),
            deg.value(kind="spread_poisoned"),
            wm.events_dispatched.value(),
            wm.predicate_checks.value(),
            *self._cache_totals(backing),
            *self._policy_totals(),
            metrics.solve_duration.count(),
            metrics.solve_duration.sum(),
            metrics.solver_shortlist_pods.value(),
            metrics.solver_shortlist_fallbacks.value(),
            metrics.solver_blocks_scanned.value(),
            metrics.solver_blocks_pruned.value(),
            metrics.solver_wave_commits.value(),
            metrics.solver_wave_replays.value(),
            metrics.solver_pallas_solves.value(),
            sum(metrics.solver_pallas_fallbacks._values.values()),
            metrics.prep_duration.sum(),
            metrics.plane_bytes.value(),
            metrics.class_split_fallbacks.value(),
            sum(metrics.shard_tensor_rebuilds._values.values()),
            sum(metrics.shard_solve_seconds._values.values()),
            metrics.cross_shard_reductions.value(),
            metrics.serving_fast_path_pods.value(),
            metrics.serving_coalesced_batches.value(),
            metrics.resident_plane_refreshes.value(),
            metrics.resident_plane_refresh.sum(),
            metrics.solver_optimal_solves.value(),
            metrics.solver_optimal_fallbacks.value(),
            metrics.slice_gangs_bound.value(),
            metrics.topology_plane_rebuilds.value(),
            metrics.attempt_window().mark())

    def _end_measure(self, result: WorkloadResult,
                     metrics: SchedulerMetrics,
                     backing, window: tuple, count: int) -> None:
        (hist_base, t0, fallback_base, poisoned_base,
         dispatched_base, checks_base, cache_hits_base, cache_miss_base,
         evals_base, idx_hits_base, idx_res_base, idx_rb_base,
         audits_base, audit_drop_base,
         solve_chunks_base, solve_s_base, sl_pods_base,
         sl_fall_base, blk_scan_base, blk_prune_base,
         wave_com_base, wave_rep_base,
         pallas_base, pallas_fb_base,
         prep_s_base, plane_b_base, class_fb_base,
         shard_rb_base, shard_s_base, xshard_base,
         fast_base, coalesced_base, refresh_base, refresh_s_base,
         opt_base, opt_fb_base,
         slice_gangs_base, topo_rb_base,
         window_mark) = window
        dt = time.monotonic() - t0
        result.measured_pods = count
        result.measured_seconds = dt
        result.throughput = count / dt if dt > 0 else 0.0
        h = metrics.attempt_duration
        labels = {"result": "scheduled", "profile": "default-scheduler"}
        result.attempt_p50 = h.percentile_since(0.50, hist_base, **labels)
        result.attempt_p90 = h.percentile_since(0.90, hist_base, **labels)
        result.attempt_p99 = h.percentile_since(0.99, hist_base, **labels)
        # TRUE order-statistic percentiles over the measured window (the
        # exact recorder riding attempt_duration's observe path); the
        # bucket-edge values above remain only as the fallback when no
        # scheduled attempt landed in the window.
        win = metrics.attempt_window()
        exact = win.percentiles_since(window_mark,
                                      (0.50, 0.90, 0.99, 0.999))
        import math
        if not math.isnan(exact[0.50]):
            result.attempt_p50 = exact[0.50]
            result.attempt_p90 = exact[0.90]
            result.attempt_p99 = exact[0.99]
            result.attempt_p999 = exact[0.999]
            result.attempt_percentiles_exact = True
        deg = metrics.backend_degradations
        result.host_fallback_pods = int(
            deg.value(kind="host_fallback") - fallback_base)
        result.spread_poisoned_pods = int(
            deg.value(kind="spread_poisoned") - poisoned_base)
        wm = backing.watch_metrics
        result.watch_events_dispatched_total = int(
            wm.events_dispatched.value() - dispatched_base)
        result.watch_predicate_checks_total = int(
            wm.predicate_checks.value() - checks_base)
        hits, misses = self._cache_totals(backing)
        result.watch_cache_hits_total = int(hits - cache_hits_base)
        result.watch_cache_misses_total = int(misses - cache_miss_base)
        (evals, idx_hits, idx_res, idx_rb,
         audits, audit_drops) = self._policy_totals()
        result.policy_evaluations_total = int(evals - evals_base)
        result.policy_index_hits_total = int(idx_hits - idx_hits_base)
        result.policy_index_residue_scans_total = int(
            idx_res - idx_res_base)
        result.policy_index_rebuilds_total = int(idx_rb - idx_rb_base)
        result.audit_events_total = int(audits - audits_base)
        result.audit_events_dropped_total = int(
            audit_drops - audit_drop_base)
        result.solver_solve_chunks = int(
            metrics.solve_duration.count() - solve_chunks_base)
        result.solver_solve_seconds_total = \
            metrics.solve_duration.sum() - solve_s_base
        result.solver_scan_width = int(metrics.solver_scan_width.value())
        result.solver_shortlist_pods_total = int(
            metrics.solver_shortlist_pods.value() - sl_pods_base)
        result.solver_shortlist_fallbacks_total = int(
            metrics.solver_shortlist_fallbacks.value() - sl_fall_base)
        result.solver_blocks_scanned_total = int(
            metrics.solver_blocks_scanned.value() - blk_scan_base)
        result.solver_blocks_pruned_total = int(
            metrics.solver_blocks_pruned.value() - blk_prune_base)
        result.solver_wave_width = int(metrics.solver_wave_width.value())
        result.solver_wave_commits_total = int(
            metrics.solver_wave_commits.value() - wave_com_base)
        result.solver_wave_replays_total = int(
            metrics.solver_wave_replays.value() - wave_rep_base)
        result.solver_pallas_solves_total = int(
            metrics.solver_pallas_solves.value() - pallas_base)
        result.solver_pallas_fallbacks_total = int(
            sum(metrics.solver_pallas_fallbacks._values.values())
            - pallas_fb_base)
        if self.backend is not None:
            from kubernetes_tpu.ops.backend import solve_provenance
            result.solve_provenance = solve_provenance()
        result.prep_seconds_total = \
            metrics.prep_duration.sum() - prep_s_base
        result.plane_classes_per_chunk = int(
            metrics.plane_classes.value())
        result.plane_bytes_uploaded_total = int(
            metrics.plane_bytes.value() - plane_b_base)
        result.class_split_fallback_pods = int(
            metrics.class_split_fallbacks.value() - class_fb_base)
        result.shard_count = int(getattr(backing, "node_shards", 1))
        result.shard_tensor_rebuilds_total = int(
            sum(metrics.shard_tensor_rebuilds._values.values())
            - shard_rb_base)
        result.shard_solve_seconds = \
            sum(metrics.shard_solve_seconds._values.values()) - shard_s_base
        result.cross_shard_reductions_total = int(
            metrics.cross_shard_reductions.value() - xshard_base)
        result.serving_fast_path_pods_total = int(
            metrics.serving_fast_path_pods.value() - fast_base)
        result.serving_coalesced_batches_total = int(
            metrics.serving_coalesced_batches.value() - coalesced_base)
        result.resident_plane_refreshes_total = int(
            metrics.resident_plane_refreshes.value() - refresh_base)
        result.resident_plane_refresh_seconds_total = \
            metrics.resident_plane_refresh.sum() - refresh_s_base
        result.solver_optimal_solves_total = int(
            metrics.solver_optimal_solves.value() - opt_base)
        result.solver_optimal_fallbacks_total = int(
            metrics.solver_optimal_fallbacks.value() - opt_fb_base)
        result.slice_gangs_bound_total = int(
            metrics.slice_gangs_bound.value() - slice_gangs_base)
        result.topology_plane_rebuilds_total = int(
            metrics.topology_plane_rebuilds.value() - topo_rb_base)
        result.slice_fragmentation_pct = \
            metrics.slice_fragmentation_pct.value()
        # Gauge is base-unit seconds now (metrics lint); the detail JSON
        # field keeps its ms name for report continuity.
        result.admission_window_ms = 1e3 * metrics.admission_window.value()

    async def _wait_bound(self, bound_keys: set, want: int,
                          deadline: float) -> None:
        """barrierOp: block until every created pod has a nodeName."""
        while time.monotonic() < deadline:
            if len(bound_keys) >= want:
                return
            await asyncio.sleep(0.01)
        raise TimeoutError(
            f"barrier: {len(bound_keys)}/{want} pods bound at timeout")

    @staticmethod
    async def _wait_keys(bound_keys: set, want: set,
                         deadline: float) -> None:
        """Scoped barrier: block until a specific key set is bound."""
        while time.monotonic() < deadline:
            if want <= bound_keys:
                return
            await asyncio.sleep(0.01)
        missing = len(want - bound_keys)
        raise TimeoutError(f"scoped barrier: {missing} pods unbound at timeout")

    @staticmethod
    def _fragmentation(sched: Scheduler) -> float:
        """Mean free-capacity fraction across nodes (%, lower = tighter)."""
        snapshot = sched.cache.update_snapshot()
        if not len(snapshot):
            return 0.0
        total = 0.0
        for ni in snapshot:
            fracs = []
            for r, alloc in ni.allocatable.res.items():
                if alloc > 0:
                    fracs.append(
                        max(0.0, (alloc - ni.requested.get(r)) / alloc))
            total += sum(fracs) / len(fracs) if fracs else 1.0
        return 100.0 * total / len(snapshot)

    @staticmethod
    def _fragmentation_occupied(sched: Scheduler) -> float:
        """Mean free-capacity fraction across OCCUPIED nodes (%, the r20
        packing metric — ops/solver.fragmentation_occupied's host twin):
        the all-nodes figure is placement-invariant once every pod
        places; this one drops when the same pods pack fewer, fuller
        nodes. Empty cluster → 0.0."""
        snapshot = sched.cache.update_snapshot()
        total = 0.0
        occupied = 0
        for ni in snapshot:
            if not ni.pods:
                continue
            occupied += 1
            fracs = []
            for r, alloc in ni.allocatable.res.items():
                if alloc > 0:
                    fracs.append(
                        max(0.0, (alloc - ni.requested.get(r)) / alloc))
            total += sum(fracs) / len(fracs) if fracs else 1.0
        return 100.0 * total / occupied if occupied else 0.0


def _result_count(metrics: SchedulerMetrics, result: str) -> int:
    return int(metrics.schedule_attempts.value(
        result=result, profile="default-scheduler"))


def load_config(path: str) -> list[dict]:
    import yaml
    with open(path) as f:
        return yaml.safe_load(f)


def run_suite(config: list[dict], backend_factory=None, batch_size: int = 1,
              filter_name: str | None = None, timeout: float = 600.0,
              through_apiserver=False) -> dict[str, dict]:
    """Run every (testcase × workload) pair, like BenchmarkPerfScheduling."""
    out: dict[str, dict] = {}
    for case in config:
        for wl in case.get("workloads") or [{"name": "default", "params": {}}]:
            full = f"{case['name']}/{wl['name']}"
            if filter_name and filter_name not in full:
                continue
            backend = backend_factory() if backend_factory else None
            # Per-family runner settings: a family may pin the apiserver
            # boundary and a policy/audit load (PolicyScale carries the
            # 1k-tenant set) so headline rows are reproducible from
            # config alone.
            runner = PerfRunner(backend=backend, batch_size=batch_size,
                                scheduler_config=case.get("schedulerConfig"),
                                through_apiserver=case.get(
                                    "throughApiserver", through_apiserver),
                                policy_count=case.get("policyCount", 0),
                                policy_tenants=case.get(
                                    "policyTenants", 0),
                                audit_rules=[{"level": case["auditLevel"]}]
                                if case.get("auditLevel") else None)
            res = asyncio.run(runner.run(
                case["workloadTemplate"], wl.get("params") or {},
                timeout=timeout))
            out[full] = res.as_dict()
    return out


def main(argv: list[str] | None = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("config", help="workload YAML")
    ap.add_argument("--backend", choices=["host", "tpu"], default="host")
    ap.add_argument("--batch-size", type=int, default=1)
    ap.add_argument("--chunk", type=int, default=None,
                    help="OVERRIDE the backend solve chunk (jit batch "
                         "signature); default lets the adaptive tuner "
                         "choose per measured latency/dirty ratio")
    ap.add_argument("--filter", default=None)
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-workload deadline in seconds (the 20k-agent "
                         "family boots longer than the 600s default)")
    ap.add_argument("--through-apiserver", choices=["", "http", "wire"],
                    default="",
                    help="cross the process boundary: all traffic (agent "
                         "watches included) rides the chosen apiserver "
                         "wire instead of direct store calls")
    args = ap.parse_args(argv)

    factory = None
    batch = args.batch_size
    if args.backend == "tpu":
        from kubernetes_tpu.ops import TPUBackend
        batch = max(batch, 128)
        chunk = None if args.chunk is None \
            else max(min(args.chunk, batch), 2)
        factory = lambda: TPUBackend(max_batch=chunk)  # noqa: E731
    boundary = {"": False, "http": True, "wire": "wire"}[
        args.through_apiserver]
    results = run_suite(load_config(args.config), backend_factory=factory,
                        batch_size=batch, filter_name=args.filter,
                        timeout=args.timeout, through_apiserver=boundary)
    print(json.dumps(results, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
