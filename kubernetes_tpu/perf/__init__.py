"""scheduler_perf harness (SURVEY §3.5)."""

from kubernetes_tpu.perf.scheduler_perf import PerfRunner, WorkloadResult, run_suite

__all__ = ["PerfRunner", "WorkloadResult", "run_suite"]
