"""Mesh sharding for the scheduling tensors (SURVEY §2.8 / §5.7)."""

from kubernetes_tpu.parallel.mesh import (
    NODES_AXIS,
    PODS_AXIS,
    SLICE_AXIS,
    build_mesh,
    build_mesh_2d,
    build_multislice_mesh,
    pad_axis,
)
from kubernetes_tpu.parallel.sharded import (
    sharded_greedy_assign,
    sharded_greedy_assign_multislice,
    sharded_masks_scores,
    sharded_sinkhorn_plan,
)

__all__ = [
    "NODES_AXIS", "PODS_AXIS", "SLICE_AXIS",
    "build_mesh", "build_mesh_2d", "build_multislice_mesh", "pad_axis",
    "sharded_greedy_assign", "sharded_greedy_assign_multislice",
    "sharded_masks_scores", "sharded_sinkhorn_plan",
]
