"""Mesh-sharded variants of the batched scheduling step.

Two phases, mirroring ops/backend._mask_and_solve exactly (same inputs, same
split of capacity-independent vs live-rescored score components):

1. `sharded_masks_scores` — the (P×N) mask + static-score phase under `jit`
   with `NamedSharding` constraints on a 2-D (pods × nodes) mesh: pure data
   parallelism, XLA inserts no collectives beyond layout changes. This is
   the DP×TP-analog fan-out replacing the reference's 16-goroutine
   `parallelize.Until` (SURVEY §2.8 row 1). Returns (mask, feasible,
   static_scores) where static_scores = host rows + weighted taint score —
   the capacity-independent components only; fit/balanced are re-scored
   live inside the solver.

2. `sharded_greedy_assign` — the sequential-equivalent solver under
   `shard_map` over the nodes axis: node state (free capacity, scores) lives
   sharded; each scan step computes its shard-local best candidate and
   resolves the global winner with `pmax`/`pmin` over ICI — the cross-shard
   argmax reduction pattern of SURVEY §5.7. Pod vectors are replicated
   (they're O(R) small). The winning shard debits its local capacity; the
   chosen index is identical on every shard by construction.

Both are mesh-size-agnostic (a (1,)-mesh degrades to the single-chip path)
and compile once per (mesh, strategy) — jitted programs are cached on the
hashable Mesh itself, with scalar weights as traced arguments.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.ops import kernels
from kubernetes_tpu.parallel.mesh import NODES_AXIS, PODS_AXIS, SLICE_AXIS

try:  # jax>=0.8 top-level; fall back for older versions
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

_INT_MAX = jnp.int32(2**31 - 1)

_PHASE_CACHE: dict = {}
_SOLVER_CACHE: dict = {}


# ---------------------------------------------------------------------------
# phase 1: masks + static scores (2-D pods × nodes mesh)
# ---------------------------------------------------------------------------

def sharded_masks_scores(mesh: Mesh, alloc_q, used_q, used_nz_q, alloc_pods,
                         used_pods, req_q, req_nz_q, untol_f, untol_p,
                         taint_f_mat, taint_p_mat, static_mask, host_scores,
                         w_taint, taint_filter_on: bool, strategy: str):
    """Mask + capacity-independent score phase, sharded (pods × nodes).

    Mirrors the first half of ops/backend._mask_and_solve: returns
    (mask (P,N), feasible (P,N), static_scores (P,N)) with mask excluding
    capacity (the solver re-checks capacity live) and static_scores =
    host_scores + w_taint × taint score over the feasible set.
    """
    phase = _masks_scores_phase(mesh, strategy)
    return phase(alloc_q, used_q, used_nz_q, alloc_pods, used_pods, req_q,
                 req_nz_q, untol_f, untol_p, taint_f_mat, taint_p_mat,
                 static_mask, host_scores, jnp.float32(w_taint),
                 jnp.bool_(taint_filter_on))


def _masks_scores_phase(mesh: Mesh, strategy: str):
    """Jitted phase cached per (mesh, strategy) — pjit rejects kwargs when
    in_shardings is given, so the static strategy lives in the closure."""
    key = (mesh, strategy)
    fn = _PHASE_CACHE.get(key)
    if fn is not None:
        return fn
    pn = NamedSharding(mesh, P(PODS_AXIS, NODES_AXIS))
    n_r = NamedSharding(mesh, P(NODES_AXIS, None))
    n_ = NamedSharding(mesh, P(NODES_AXIS))
    p_r = NamedSharding(mesh, P(PODS_AXIS, None))

    @partial(jax.jit,
             in_shardings=(n_r, n_r, n_r, n_, n_, p_r, p_r, p_r, p_r,
                           n_r, n_r, pn, pn, None, None),
             out_shardings=(pn, pn, pn))
    def phase(alloc_q, used_q, used_nz_q, alloc_pods, used_pods, req_q,
              req_nz_q, untol_f, untol_p, taint_f_mat, taint_p_mat,
              static_mask, host_scores, w_taint, taint_filter_on):
        fit0 = kernels.fit_filter_mask(
            alloc_q, used_q, used_pods, alloc_pods, req_q)
        taint_ok = kernels.taint_filter_mask(taint_f_mat, untol_f)
        taint_ok = taint_ok | jnp.logical_not(taint_filter_on)
        mask = static_mask & taint_ok
        feasible = mask & fit0
        static_scores = host_scores + w_taint * kernels.taint_toleration_score(
            taint_p_mat, untol_p, feasible)
        return mask, feasible, static_scores

    _PHASE_CACHE[key] = phase
    return phase


# ---------------------------------------------------------------------------
# phase 2: sequential-equivalent solver (1-D nodes mesh)
# ---------------------------------------------------------------------------

def sharded_greedy_assign(mesh: Mesh, req_q, req_nz_q, free_q, free_pods,
                          used_nz_q, alloc_q, mask, static_scores,
                          fit_col_w, bal_col_mask, shape_u, shape_s,
                          w_fit, w_bal, strategy: str):
    """Sequential-equivalent greedy with live re-scoring, node axis sharded.

    Per scan step: shard-local candidate (max score, min index among ties) →
    global winner via `pmax` then `pmin` over the nodes axis → winning shard
    debits capacity. Semantics match ops/solver.greedy_assign_rescoring
    exactly (ties → lowest global node index)."""
    n_shards = mesh.shape[NODES_AXIS]
    n_total = free_q.shape[0]
    assert n_total % n_shards == 0, (n_total, n_shards)
    run = _solver_fn(mesh, strategy, n_total // n_shards)
    return run(req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q,
               mask, static_scores, fit_col_w, bal_col_mask,
               jnp.asarray(shape_u), jnp.asarray(shape_s),
               jnp.float32(w_fit), jnp.float32(w_bal))


def _solver_fn(mesh: Mesh, strategy: str, local_n: int,
               axes: tuple[str, ...] = (NODES_AXIS,)):
    """One solver body for every mesh shape: the node dimension shards over
    `axes` (flattened, first axis major). Reductions run innermost-axis
    first, so a (slice, nodes) pair reduces slice-locally over ICI before
    ONE scalar per slice crosses DCN — the hierarchical argmax of SURVEY
    §5.7 falls out of the axis order."""
    key = (mesh, strategy, local_n, axes)
    fn = _SOLVER_CACHE.get(key)
    if fn is not None:
        return fn

    spec_nr = P(axes, None)
    spec_n = P(axes)
    spec_pn = P(None, axes)
    rep = P()

    def _reduce(val, op):
        for a in reversed(axes):  # innermost (ICI) first, outermost last
            val = op(val, a)
        return val

    @jax.jit
    @partial(shard_map, mesh=mesh,
             in_specs=(rep, rep, spec_nr, spec_n, spec_nr, spec_nr,
                       spec_pn, spec_pn, rep, rep, rep, rep, rep, rep),
             out_specs=rep, check_vma=False)
    def run(req_q, req_nz_q, free_q, free_pods, used_nz, alloc_q,
            mask, static_sc, fit_col_w, bal_col_mask, shape_u, shape_s,
            w_fit, w_bal):
        shard = jnp.int32(0)
        for a in axes:
            shard = shard * lax.axis_size(a) + lax.axis_index(a)
        base = (shard * local_n).astype(jnp.int32)
        iota = jnp.arange(local_n, dtype=jnp.int32)

        def step(carry, inp):
            free_q, free_pods, used_nz = carry
            req, req_nz, m, sc_static = inp
            fits = m & jnp.all(req[None, :] <= free_q, axis=1) & (free_pods >= 1)
            sc = sc_static
            sc = sc + w_fit * kernels.fit_score(
                alloc_q, used_nz, req_nz[None, :], fit_col_w, strategy,
                shape_u, shape_s)[0]
            sc = sc + w_bal * kernels.balanced_allocation_score(
                alloc_q, used_nz, req_nz[None, :], bal_col_mask)[0]
            masked = jnp.where(fits, sc, -jnp.inf)
            gbest = _reduce(jnp.max(masked), lax.pmax)
            # Tie-break: lowest global index among shards holding gbest.
            cand = jnp.where(masked >= gbest, iota + base, _INT_MAX)
            gidx = _reduce(jnp.min(cand), lax.pmin)
            chosen = jnp.where(jnp.isfinite(gbest), gidx, jnp.int32(-1))
            hit = (iota + base) == chosen
            free_q = free_q - jnp.where(hit[:, None], req[None, :], 0)
            free_pods = free_pods - hit.astype(jnp.int32)
            used_nz = used_nz + jnp.where(hit[:, None], req_nz[None, :], 0)
            return (free_q, free_pods, used_nz), chosen

        (_, _, _), assign = lax.scan(
            step, (free_q, free_pods, used_nz),
            (req_q, req_nz_q, mask, static_sc))
        return assign

    _SOLVER_CACHE[key] = run
    return run


# ---------------------------------------------------------------------------
# phase 2b: multi-slice solver (2-D slice × nodes mesh — config #5)
# ---------------------------------------------------------------------------

def sharded_greedy_assign_multislice(mesh: Mesh, req_q, req_nz_q, free_q,
                                     free_pods, used_nz_q, alloc_q, mask,
                                     static_scores, fit_col_w, bal_col_mask,
                                     shape_u, shape_s, w_fit, w_bal,
                                     strategy: str):
    """Sequential-equivalent greedy over a (slice × nodes) mesh: the same
    solver body as `sharded_greedy_assign`, with the node dimension sharded
    over BOTH axes and the per-step argmax reduced hierarchically —
    slice-local `pmax` over ICI, then ONE scalar per slice across DCN, so
    cross-slice traffic is O(1) per pod regardless of node count (the 50k
    config #5 enabler). Tie-break matches the single-device solver."""
    s_shards = mesh.shape[SLICE_AXIS]
    n_shards = mesh.shape[NODES_AXIS]
    n_total = free_q.shape[0]
    shards = s_shards * n_shards
    assert n_total % shards == 0, (n_total, shards)
    run = _solver_fn(mesh, strategy, n_total // shards,
                     axes=(SLICE_AXIS, NODES_AXIS))
    return run(req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q,
               mask, static_scores, fit_col_w, bal_col_mask,
               jnp.asarray(shape_u), jnp.asarray(shape_s),
               jnp.float32(w_fit), jnp.float32(w_bal))
