"""Mesh-sharded variants of the batched scheduling step.

Two phases, mirroring ops/backend._mask_and_solve exactly (same inputs, same
split of capacity-independent vs live-rescored score components):

1. `sharded_masks_scores` — the (P×N) mask + static-score phase under `jit`
   with `NamedSharding` constraints on a 2-D (pods × nodes) mesh: pure data
   parallelism, XLA inserts no collectives beyond layout changes. This is
   the DP×TP-analog fan-out replacing the reference's 16-goroutine
   `parallelize.Until` (SURVEY §2.8 row 1). Returns (mask, feasible,
   static_scores) where static_scores = host rows + weighted taint score —
   the capacity-independent components only; fit/balanced are re-scored
   live inside the solver.

2. `sharded_greedy_assign` — the sequential-equivalent solver under
   `shard_map` over the nodes axis: node state (free capacity, scores) lives
   sharded; each scan step computes its shard-local best candidate and
   resolves the global winner with `pmax`/`pmin` over ICI — the cross-shard
   argmax reduction pattern of SURVEY §5.7. Pod vectors are replicated
   (they're O(R) small). The winning shard debits its local capacity; the
   chosen index is identical on every shard by construction.

Both are mesh-size-agnostic (a (1,)-mesh degrades to the single-chip path)
and compile once per (mesh, strategy) — jitted programs are cached on the
hashable Mesh itself, with scalar weights as traced arguments.

Pairing with the sharded CONTROL plane (store/sharded.py, r13): the
per-shard host prep maintains the node axis in GLOBAL order (hash shards
own scattered row sets, never reordered), so the arrays these solvers
consume are the same ones the single-store path produces — the device
mesh is free to block-partition that axis over chips while the control
plane hash-partitions it over stores, and the per-step `pmax`/`pmin`
winner reduction below IS the cross-shard argmax of both decompositions
(assignments stay bit-identical to the unsharded path by the index tie
rule; tests/test_sharded_parity.py pins it end to end).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.ops import kernels, pallas_kernel, solver
from kubernetes_tpu.parallel.mesh import NODES_AXIS, PODS_AXIS, SLICE_AXIS

try:  # jax>=0.8 top-level; fall back for older versions
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

# The replication-check kwarg was renamed across jax versions
# (check_rep → check_vma); pass whichever this jax understands.
import inspect as _inspect

_params = _inspect.signature(shard_map).parameters
_SHARD_MAP_KW = {"check_vma": False} if "check_vma" in _params else (
    {"check_rep": False} if "check_rep" in _params else {})

_INT_MAX = jnp.int32(2**31 - 1)

_PHASE_CACHE: dict = {}
_SOLVER_CACHE: dict = {}


def _block_w_for(block_w: int, shortlist_k: int, local_n: int) -> int:
    """Clamp a requested block-index width to a shard-local shape it is
    valid for: the two-pass prefilter needs M+1 ≤ B over the SHARD'S
    column count (ops/solver.block_bound_prefilter's static guard — a
    shard too narrow to leave one block unselected has nothing to
    prune). 0 keeps the full-width local prefilter, structurally."""
    if not (block_w and shortlist_k):
        return 0
    b = -(-local_n // block_w)
    m = 2 * (-(-(shortlist_k + 1) // block_w))
    return block_w if m + 1 <= b else 0


# ---------------------------------------------------------------------------
# phase 1: masks + static scores (2-D pods × nodes mesh)
# ---------------------------------------------------------------------------

def sharded_masks_scores(mesh: Mesh, alloc_q, used_q, used_nz_q, alloc_pods,
                         used_pods, req_q, req_nz_q, untol_f, untol_p,
                         taint_f_mat, taint_p_mat, static_mask, host_scores,
                         w_taint, taint_filter_on: bool, strategy: str):
    """Mask + capacity-independent score phase, sharded (pods × nodes).

    Mirrors the first half of ops/backend._mask_and_solve: returns
    (mask (P,N), feasible (P,N), static_scores (P,N)) with mask excluding
    capacity (the solver re-checks capacity live) and static_scores =
    host_scores + w_taint × taint score over the feasible set.
    """
    phase = _masks_scores_phase(mesh, strategy)
    return phase(alloc_q, used_q, used_nz_q, alloc_pods, used_pods, req_q,
                 req_nz_q, untol_f, untol_p, taint_f_mat, taint_p_mat,
                 static_mask, host_scores, jnp.float32(w_taint),
                 jnp.bool_(taint_filter_on))


def _masks_scores_phase(mesh: Mesh, strategy: str):
    """Jitted phase cached per (mesh, strategy) — pjit rejects kwargs when
    in_shardings is given, so the static strategy lives in the closure."""
    key = (mesh, strategy)
    fn = _PHASE_CACHE.get(key)
    if fn is not None:
        return fn
    pn = NamedSharding(mesh, P(PODS_AXIS, NODES_AXIS))
    n_r = NamedSharding(mesh, P(NODES_AXIS, None))
    n_ = NamedSharding(mesh, P(NODES_AXIS))
    p_r = NamedSharding(mesh, P(PODS_AXIS, None))

    @partial(jax.jit,
             in_shardings=(n_r, n_r, n_r, n_, n_, p_r, p_r, p_r, p_r,
                           n_r, n_r, pn, pn, None, None),
             out_shardings=(pn, pn, pn))
    def phase(alloc_q, used_q, used_nz_q, alloc_pods, used_pods, req_q,
              req_nz_q, untol_f, untol_p, taint_f_mat, taint_p_mat,
              static_mask, host_scores, w_taint, taint_filter_on):
        fit0 = kernels.fit_filter_mask(
            alloc_q, used_q, used_pods, alloc_pods, req_q)
        taint_ok = kernels.taint_filter_mask(taint_f_mat, untol_f)
        taint_ok = taint_ok | jnp.logical_not(taint_filter_on)
        mask = static_mask & taint_ok
        feasible = mask & fit0
        static_scores = host_scores + w_taint * kernels.taint_toleration_score(
            taint_p_mat, untol_p, feasible)
        return mask, feasible, static_scores

    _PHASE_CACHE[key] = phase
    return phase


# ---------------------------------------------------------------------------
# phase 2: sequential-equivalent solver (1-D nodes mesh)
# ---------------------------------------------------------------------------

def sharded_greedy_assign(mesh: Mesh, req_q, req_nz_q, free_q, free_pods,
                          used_nz_q, alloc_q, mask, static_scores,
                          fit_col_w, bal_col_mask, shape_u, shape_s,
                          w_fit, w_bal, strategy: str,
                          shortlist_k: int = 0, rows=None, exc=None,
                          row_req_q=None, row_req_nz_q=None,
                          wave_w: int = 0, pallas: bool = False,
                          block_w: int = 0):
    """Sequential-equivalent greedy with live re-scoring, node axis sharded.

    Per scan step: shard-local candidate (max score, min index among ties) →
    global winner via `pmax` then `pmin` over the nodes axis → winning shard
    debits capacity. Semantics match ops/solver.greedy_assign_rescoring
    exactly (ties → lowest global node index).

    shortlist_k > 0 prunes SHARD-LOCALLY before the cross-shard argmax:
    each shard prefilters its own top-K columns per pod (by shard-local
    chunk-start score) and re-scores only those plus its locally-debited
    nodes per step, with the same per-step exactness bound check and full
    local-row fallback as ops/solver's shortlist scans — so the local
    candidate entering the `pmax` is always the true shard maximum and the
    global winner is bit-identical. The per-step ICI reduction was already
    O(1) scalars; what shrinks is each shard's local reduce, N/devices →
    K/devices + touched. A shard narrower than K+1 columns keeps the full
    local scan (nothing to prune).

    block_w > 0 additionally routes each shard's PREFILTER through the
    two-pass block-sparse form (ops/solver.block_bound_prefilter) over
    its own column set: an O(C·B_local) bound scan gates which local
    columns the chunk-start pass touches, with the in-program full-width
    fallback whenever the exactness predicate fails — shard-local and
    collective-free, so the per-step pmax/pmin winner wire is untouched
    and assignments stay bit-identical at every shard count. A shard
    whose column count cannot satisfy the M+1 ≤ B_local shape guard
    keeps the full-width local prefilter (same clamp rule as the
    backend's tuner row).

    pallas=True fuses each wave's shard-local (W, local_n) evaluation —
    plane gather, exception gate, capacity fit, live re-score, feasible
    masking — into one Pallas kernel per wave step
    (ops/pallas_kernel.wave_eval). Everything that crosses the mesh is
    UNCHANGED: the W pmax/pmin winner rounds, the global-coordinate
    conflict OR-reduce, and the commit/replay cond stay in the shard_map
    body (SURVEY §5.8's ICI reduction contract), so assignments remain
    bit-identical at every shard count. The shortlist path keeps its
    W=1 scan (shortlist_k wins when both are set), as before.

    Class-dictionary planes (the r14 format): `mask`/`static_scores` may
    carry C CLASS rows instead of P pod rows — pass `rows` ((P,) pod →
    plane row), `row_req_q`/`row_req_nz_q` ((C,R) per-row request
    vectors, used by the shard-local prefilter so it too runs over C
    rows), and optionally `exc` ((P,) GLOBAL single-allowed-column
    exception, -1 = none). Defaults reproduce the per-pod form
    (rows = arange, row_req = req).

    wave_w > 1 runs the SPECULATIVE WAVEFRONT form of the same solver
    (the r18 scan — see ops/solver.py): W pods per scan step, each
    wave's prefix-distinct argmax resolved under the SAME per-step
    `pmax`/`pmin` shard reduction (W rounds per wave instead of one per
    pod), conflicts detected in GLOBAL node coordinates (each commit's
    owner shard re-scores it for later members; the (W,) conflict bits
    OR-reduce across the mesh so every shard takes the same
    fast-commit/serial-replay branch) — assignments bit-identical to the
    serial sharded scan at every W and every shard count. Composes with
    class planes and exceptions; the shortlist path keeps its W=1 scan
    (shortlist_k wins when both are set)."""
    n_shards = mesh.shape[NODES_AXIS]
    n_total = free_q.shape[0]
    assert n_total % n_shards == 0, (n_total, n_shards)
    local_n = n_total // n_shards
    k = min(shortlist_k, local_n - 1) if shortlist_k else 0
    run = _solver_fn(mesh, strategy, local_n, shortlist_k=max(k, 0),
                     wave_w=0 if k else max(0, wave_w),
                     pallas=bool(pallas and not k and wave_w > 1),
                     block_w=_block_w_for(block_w, k, local_n))
    p = req_q.shape[0]
    if rows is None:
        rows = jnp.arange(p, dtype=jnp.int32)
    if exc is None:
        exc = jnp.full((p,), -1, dtype=jnp.int32)
    if row_req_q is None:
        row_req_q = req_q
    if row_req_nz_q is None:
        row_req_nz_q = req_nz_q
    return run(req_q, req_nz_q, jnp.asarray(rows), jnp.asarray(exc),
               jnp.asarray(row_req_q), jnp.asarray(row_req_nz_q),
               free_q, free_pods, used_nz_q, alloc_q,
               mask, static_scores, fit_col_w, bal_col_mask,
               jnp.asarray(shape_u), jnp.asarray(shape_s),
               jnp.float32(w_fit), jnp.float32(w_bal))


def _wave_body(mesh, axes, local_n, base, iota, strategy, wave_w,
               local_full, _reduce,
               req_q, req_nz_q, rows, exc, free_q, free_pods, used_nz,
               alloc_q, mask, static_sc, fit_col_w, bal_col_mask,
               shape_u, shape_s, w_fit, w_bal, pallas: bool = False):
    """The wavefront wave-step body of the sharded solver (traced inside
    the shard_map `run`; see sharded_greedy_assign's wave_w contract).

    Per wave: ONE shard-local (W, local_n) evaluation against the carry,
    then W prefix-distinct global argmax rounds (the same `pmax`→`pmin`
    winner reduction the serial step runs once per pod, with earlier
    picks masked out on their owner shard), a conflict check in GLOBAL
    coordinates — each pick's owner shard re-scores it after its debit
    for every later member, and the (W,W) beats matrix OR-reduces over
    the mesh into replicated (W,) conflict bits — and a replicated-
    predicate cond: fast vectorized commit (owners scatter their picks'
    debits) or the serial replay (the one-pod step body, W times, exact).
    Speculative picks and the replay share the serial tie rule (lowest
    GLOBAL node index among max scorers), so assignments match the
    serial sharded scan bit-for-bit at every W and shard count."""
    from kubernetes_tpu.ops.solver import _wave_split

    p = req_q.shape[0]
    W = max(1, min(wave_w, p))
    ex = jnp.full((p,), -1, jnp.int32) if exc is None else exc
    (req_w, req_nz_w, rows_w, ex_w), real_w, _ = _wave_split(
        W, (req_q, req_nz_q, rows, ex))
    w_iota = jnp.arange(W, dtype=jnp.int32)
    interp = pallas_kernel.default_interpret() if pallas else True

    def wave_step(carry, inp):
        free_q, free_pods, used_nz = carry
        req, req_nz, row, e, real = inp
        el = e - base                                   # local exc coords
        if pallas:
            # Fused shard-local evaluation: same op sequence, one
            # kernel — the inline form below is the bit-identical
            # reference (tests/test_pallas_solver.py).
            masked, m = pallas_kernel.wave_eval(
                mask, static_sc, alloc_q, free_q, free_pods, used_nz,
                req, req_nz, row, e, el, real, fit_col_w, bal_col_mask,
                shape_u, shape_s, w_fit, w_bal, strategy,
                interpret=interp)
        else:
            m = mask[row] \
                & ((e < 0)[:, None] | (iota[None, :] == el[:, None])) \
                & real[:, None]                         # (W, local_n)
            fits = m & jnp.all(req[:, None, :] <= free_q[None, :, :],
                               axis=-1) & (free_pods >= 1)[None, :]
            sc = static_sc[row]
            sc = sc + w_fit * kernels.fit_score(
                alloc_q, used_nz, req_nz, fit_col_w, strategy, shape_u,
                shape_s)
            sc = sc + w_bal * kernels.balanced_allocation_score(
                alloc_q, used_nz, req_nz, bal_col_mask)
            masked = jnp.where(fits, sc, -jnp.inf)
        # Prefix-distinct GLOBAL picks: per member, one local max with
        # earlier picks masked out (owner shard), then the serial step's
        # pmax/pmin winner reduction.
        bs, ys = [], []
        for w in range(W):
            rv = masked[w]
            for yp in ys:
                rv = jnp.where(iota + base == yp, -jnp.inf, rv)
            lbest = jnp.max(rv)
            lidx = jnp.min(jnp.where(rv == lbest, iota, local_n))
            gbest = _reduce(lbest, lax.pmax)
            gcand = jnp.where((lidx < local_n) & (lbest >= gbest),
                              lidx + base, _INT_MAX)
            gidx = _reduce(gcand, lax.pmin)
            ys.append(jnp.where(jnp.isfinite(gbest), gidx, _INT_MAX))
            bs.append(gbest)
        b = jnp.stack(bs)
        y = jnp.stack(ys)                               # global ids
        hit = y < _INT_MAX
        li = y - base
        own = (li >= 0) & (li < local_n)                # pick owner bits
        safe = jnp.clip(li, 0, local_n - 1)
        # Conflicts in global coordinates: the owner of each pick y_j
        # re-scores it after member j's debit for every later member w;
        # non-owners contribute False and the bits OR-reduce replicated.
        fr_j = free_q[safe] - req                       # (W,R) owner-valid
        fp_j = free_pods[safe] - 1
        unz_j = used_nz[safe] + req_nz
        al_j = alloc_q[safe]
        upd = static_sc[row[:, None], safe[None, :]] \
            + w_fit * kernels.fit_score(
                al_j, unz_j, req_nz, fit_col_w, strategy, shape_u, shape_s) \
            + w_bal * kernels.balanced_allocation_score(
                al_j, unz_j, req_nz, bal_col_mask)      # (W,W)
        cap = jnp.all(req[:, None, :] <= fr_j[None, :, :], axis=-1)
        feas = m[:, safe] & cap & (fp_j >= 1)[None, :] \
            & (hit & own)[None, :]
        beats = feas & ((upd > b[:, None])
                        | ((upd == b[:, None]) & (y[None, :] < y[:, None])))
        tri = w_iota[None, :] < w_iota[:, None]
        conflict_local = jnp.any(beats & tri, axis=1).astype(jnp.int32)
        conflict = _reduce(conflict_local, lax.pmax) > 0

        def fast(st):
            fq, fp, unz = st
            inb = own & hit
            fq = fq.at[safe].add(
                jnp.where(inb[:, None], -req, 0).astype(fq.dtype))
            fp = fp.at[safe].add(jnp.where(inb, -1, 0).astype(fp.dtype))
            unz = unz.at[safe].add(
                jnp.where(inb[:, None], req_nz, 0).astype(unz.dtype))
            return (fq, fp, unz), \
                jnp.where(hit, y, jnp.int32(-1)).astype(jnp.int32)

        def slow(st):
            fq, fp, unz = st

            def body(w, s):
                fq, fp, unz, out = s
                m_w = mask[row[w]] \
                    & ((e[w] < 0) | (iota == el[w])) & real[w]
                lbest, lidx = local_full(req[w], req_nz[w], m_w,
                                         static_sc[row[w]], fq, fp, unz)
                gbest = _reduce(lbest, lax.pmax)
                gcand = jnp.where((lidx < local_n) & (lbest >= gbest),
                                  lidx + base, _INT_MAX)
                gidx = _reduce(gcand, lax.pmin)
                chosen = jnp.where(jnp.isfinite(gbest), gidx,
                                   jnp.int32(-1))
                lw = chosen - base
                inb = (lw >= 0) & (lw < local_n)
                sf = jnp.clip(lw, 0, local_n - 1)
                fq = fq.at[sf].add(
                    jnp.where(inb, -req[w], 0).astype(fq.dtype))
                fp = fp.at[sf].add(jnp.where(inb, -1, 0).astype(fp.dtype))
                unz = unz.at[sf].add(
                    jnp.where(inb, req_nz[w], 0).astype(unz.dtype))
                return (fq, fp, unz, out.at[w].set(chosen))

            fq, fp, unz, out = lax.fori_loop(
                0, W, body, (fq, fp, unz, jnp.full((W,), -1, jnp.int32)))
            return (fq, fp, unz), out

        return lax.cond(jnp.any(conflict), slow, fast,
                        (free_q, free_pods, used_nz))

    xs = (req_w, req_nz_w, rows_w, ex_w, real_w)
    _, out = lax.scan(wave_step, (free_q, free_pods, used_nz), xs)
    return out.reshape(-1)[:p]


def _solver_fn(mesh: Mesh, strategy: str, local_n: int,
               axes: tuple[str, ...] = (NODES_AXIS,),
               shortlist_k: int = 0, wave_w: int = 0,
               pallas: bool = False, block_w: int = 0):
    """One solver body for every mesh shape: the node dimension shards over
    `axes` (flattened, first axis major). Reductions run innermost-axis
    first, so a (slice, nodes) pair reduces slice-locally over ICI before
    ONE scalar per slice crosses DCN — the hierarchical argmax of SURVEY
    §5.7 falls out of the axis order. wave_w > 1 compiles the wavefront
    wave-step body instead of the one-pod step (mutually exclusive with
    shortlist_k; the caller routes)."""
    key = (mesh, strategy, local_n, axes, shortlist_k, wave_w, pallas,
           block_w)
    fn = _SOLVER_CACHE.get(key)
    if fn is not None:
        return fn

    spec_nr = P(axes, None)
    spec_n = P(axes)
    spec_pn = P(None, axes)
    rep = P()

    def _reduce(val, op):
        for a in reversed(axes):  # innermost (ICI) first, outermost last
            val = op(val, a)
        return val

    @jax.jit
    @partial(shard_map, mesh=mesh,
             in_specs=(rep, rep, rep, rep, rep, rep,
                       spec_nr, spec_n, spec_nr, spec_nr,
                       spec_pn, spec_pn, rep, rep, rep, rep, rep, rep),
             out_specs=rep, **_SHARD_MAP_KW)
    def run(req_q, req_nz_q, rows, exc, row_req_q, row_req_nz_q,
            free_q, free_pods, used_nz, alloc_q,
            mask, static_sc, fit_col_w, bal_col_mask, shape_u, shape_s,
            w_fit, w_bal):
        shard = jnp.int32(0)
        for a in axes:
            # mesh.shape is static — lax.axis_size only exists on newer jax.
            shard = shard * mesh.shape[a] + lax.axis_index(a)
        base = (shard * local_n).astype(jnp.int32)
        iota = jnp.arange(local_n, dtype=jnp.int32)
        p_pods = req_q.shape[0]

        def local_full(req, req_nz, m, sc_static, free_q, free_pods,
                       used_nz):
            """Exact local (best score, local argmin-index) over the whole
            shard — the unpruned per-step body and the fallback branch."""
            fits = m & jnp.all(req[None, :] <= free_q, axis=1) \
                & (free_pods >= 1)
            sc = sc_static
            sc = sc + w_fit * kernels.fit_score(
                alloc_q, used_nz, req_nz[None, :], fit_col_w, strategy,
                shape_u, shape_s)[0]
            sc = sc + w_bal * kernels.balanced_allocation_score(
                alloc_q, used_nz, req_nz[None, :], bal_col_mask)[0]
            masked = jnp.where(fits, sc, -jnp.inf)
            lbest = jnp.max(masked)
            lidx = jnp.min(jnp.where(masked == lbest, iota, local_n))
            return lbest, lidx.astype(jnp.int32)

        if wave_w > 1:
            return _wave_body(
                mesh, axes, local_n, base, iota, strategy, wave_w,
                local_full, _reduce,
                req_q, req_nz_q, rows, exc, free_q, free_pods, used_nz,
                alloc_q, mask, static_sc, fit_col_w, bal_col_mask,
                shape_u, shape_s, w_fit, w_bal, pallas=pallas)

        if shortlist_k:
            # Shard-local prefilter: chunk-start scores over MY columns,
            # per-PLANE-ROW top-K + the (K+1)-th value as the local
            # threshold — C class rows when the caller ships class
            # planes, P pod rows in the identity form. block_w > 0
            # routes the two-pass block-sparse form over this shard's
            # columns: the bound scan, gather, and the in-program
            # full-width fallback are all shard-LOCAL (no collective —
            # shards may even take different cond branches), and local
            # padding columns are handled by feasibility alone
            # (n_real = local_n: a looser bound for a block holding
            # global pad columns can only cost pruning, never
            # exactness). Local-index tie rules line up exactly because
            # the gather preserves ascending local column order.
            fits0 = jnp.all(row_req_q[:, None, :] <= free_q[None, :, :],
                            axis=-1) & (free_pods >= 1)[None, :]
            if block_w:
                sc0, sl_cand, sl_t, _, _ = solver.block_bound_prefilter(
                    alloc_q, used_nz, row_req_nz_q, static_sc,
                    mask & fits0, fit_col_w, bal_col_mask, shape_u,
                    shape_s, w_fit, w_bal, strategy,
                    jnp.int32(local_n), shortlist_k, block_w)
            else:
                sc0 = kernels.chunk_start_scores(
                    alloc_q, used_nz, row_req_nz_q, static_sc, fit_col_w,
                    bal_col_mask, shape_u, shape_s, w_fit, w_bal,
                    strategy)
                vals, cand0 = lax.top_k(
                    jnp.where(mask & fits0, sc0, -jnp.inf),
                    shortlist_k + 1)
                sl_cand = cand0[:, :shortlist_k].astype(jnp.int32)
                sl_t = vals[:, shortlist_k]

        def step(carry, inp):
            if shortlist_k:
                free_q, free_pods, used_nz, touched, tidx, kstep = carry
                req, req_nz, row, e = inp
                el = e - base  # exception column in LOCAL coordinates
                cand = sl_cand[row]
                t = sl_t[row]
                cset = jnp.concatenate([cand, tidx])
                valid = cset < local_n
                ci = jnp.where(valid, cset, 0)
                # (row, ci) element gathers off the closed-over local
                # planes — an (local_n,)-wide xs row per step would put
                # O(local_n) traffic back into the pruned scan.
                live = static_sc[row, ci]
                live = live + w_fit * kernels.fit_score(
                    alloc_q[ci], used_nz[ci], req_nz[None, :], fit_col_w,
                    strategy, shape_u, shape_s)[0]
                live = live + w_bal * kernels.balanced_allocation_score(
                    alloc_q[ci], used_nz[ci], req_nz[None, :],
                    bal_col_mask)[0]
                live = jnp.where(touched[ci], live, sc0[row, ci])
                fits = mask[row, ci] & valid \
                    & jnp.all(req[None, :] <= free_q[ci], axis=1) \
                    & (free_pods[ci] >= 1) \
                    & ((e < 0) | (ci == el))
                masked = jnp.where(fits, live, -jnp.inf)
                sbest = jnp.max(masked)
                any_l = sbest > -jnp.inf
                sidx = jnp.min(jnp.where(masked == sbest, ci, local_n)
                               ).astype(jnp.int32)
                w_t = touched[jnp.minimum(sidx, local_n - 1)]
                trusted = jnp.where(
                    any_l,
                    (sbest > t) | ((sbest == t) & jnp.logical_not(w_t)),
                    t == -jnp.inf)

                def fb(_):
                    m = mask[row] & ((e < 0) | (iota == el))
                    return local_full(req, req_nz, m, static_sc[row],
                                      free_q, free_pods, used_nz)

                lbest, lidx = lax.cond(
                    trusted,
                    lambda _: (sbest,
                               jnp.where(any_l, sidx, jnp.int32(local_n))),
                    fb, None)
            else:
                free_q, free_pods, used_nz = carry
                req, req_nz, row, e = inp
                m = mask[row] & ((e < 0) | (iota == (e - base)))
                lbest, lidx = local_full(req, req_nz, m, static_sc[row],
                                         free_q, free_pods, used_nz)
            gbest = _reduce(lbest, lax.pmax)
            # Tie-break: lowest global index among shards holding gbest.
            gcand = jnp.where((lidx < local_n) & (lbest >= gbest),
                              lidx + base, _INT_MAX)
            gidx = _reduce(gcand, lax.pmin)
            chosen = jnp.where(jnp.isfinite(gbest), gidx, jnp.int32(-1))
            li = chosen - base
            inb = (li >= 0) & (li < local_n)
            safe = jnp.clip(li, 0, local_n - 1)
            free_q = free_q.at[safe].add(
                jnp.where(inb, -req, 0).astype(free_q.dtype))
            free_pods = free_pods.at[safe].add(
                jnp.where(inb, -1, 0).astype(free_pods.dtype))
            used_nz = used_nz.at[safe].add(
                jnp.where(inb, req_nz, 0).astype(used_nz.dtype))
            if shortlist_k:
                touched = touched.at[safe].set(touched[safe] | inb)
                tidx = tidx.at[kstep].set(jnp.where(inb, li, local_n))
                return (free_q, free_pods, used_nz, touched, tidx,
                        kstep + 1), chosen
            return (free_q, free_pods, used_nz), chosen

        if shortlist_k:
            carry0 = (free_q, free_pods, used_nz,
                      jnp.zeros((local_n,), jnp.bool_),
                      jnp.full((p_pods,), local_n, jnp.int32),
                      jnp.int32(0))
        else:
            carry0 = (free_q, free_pods, used_nz)
        _, assign = lax.scan(step, carry0, (req_q, req_nz_q, rows, exc))
        return assign

    _SOLVER_CACHE[key] = run
    return run


# ---------------------------------------------------------------------------
# phase 2a: Sinkhorn transport plan (optimal solve mode, nodes axis sharded)
# ---------------------------------------------------------------------------

_SINKHORN_CACHE: dict = {}


def sharded_sinkhorn_plan(mesh: Mesh, feasible, cost, row_counts, col_cap,
                          iters, temp,
                          axes: tuple[str, ...] = (NODES_AXIS,)):
    """ops/solver.sinkhorn_plan with the NODE (column) axis sharded.

    The (C,N) class planes keep C small and replicated; each shard owns
    an N/devices column block of feasible/cost and its slice of the
    column capacities. Per iteration the only cross-shard traffic is the
    row marginal `K @ v` — a (C,) psum over the mesh (innermost axis
    first, the SURVEY §5.7 hierarchical-reduction order) — plus one
    (C,) pmax up front for the row-max shift; the column update is
    purely shard-local because `u` is replicated. Same annealing
    schedule, same inequality column update, same sanitized log-plan
    output as the single-device form (tests pin allclose parity at
    {1,4,8} shards)."""
    fn = _sinkhorn_fn(mesh, axes)
    return fn(feasible, cost, row_counts, col_cap,
              jnp.int32(iters), jnp.float32(temp))


def _sinkhorn_fn(mesh: Mesh, axes: tuple[str, ...]):
    key = (mesh, axes)
    fn = _SINKHORN_CACHE.get(key)
    if fn is not None:
        return fn

    spec_cn = P(None, axes)
    spec_n = P(axes)
    rep = P()

    def _reduce(val, op):
        for a in reversed(axes):  # innermost (ICI) first, outermost last
            val = op(val, a)
        return val

    @jax.jit
    @partial(shard_map, mesh=mesh,
             in_specs=(spec_cn, spec_cn, rep, spec_n, rep, rep),
             out_specs=(spec_cn, spec_cn), **_SHARD_MAP_KW)
    def sink_run(feasible, cost, row_counts, col_cap, iters, temp):
        from kubernetes_tpu.ops.solver import SINKHORN_STAGES

        a = row_counts.astype(jnp.float32)
        b = jnp.maximum(col_cap.astype(jnp.float32), 0.0)
        eps = jnp.float32(1e-12)
        n_iters = jnp.maximum(iters, 1)
        stages = jnp.int32(SINKHORN_STAGES)
        kmask = feasible.astype(jnp.float32)
        lrmax = jnp.max(jnp.where(feasible, cost.astype(jnp.float32),
                                  -jnp.inf), axis=1, keepdims=True)
        rmax = _reduce(lrmax, lax.pmax)
        sc = jnp.where(feasible, cost.astype(jnp.float32) - rmax, 0.0)

        def kernel(stage):
            t = temp * jnp.exp2((stages - 1 - stage).astype(jnp.float32))
            return kmask * jnp.exp(sc / jnp.maximum(t, eps))

        def step(i, uv):
            u, v = uv
            k = kernel(jnp.minimum((stages * i) // n_iters, stages - 1))
            row = _reduce(k @ v, lax.psum)      # (C,) global row marginal
            u = a / jnp.maximum(row, eps)
            col = u @ k                          # shard-local: u replicated
            v = jnp.minimum(jnp.float32(1.0), b / jnp.maximum(col, eps))
            return (u, v)

        u, v = lax.fori_loop(
            0, n_iters, step,
            (jnp.ones(a.shape, jnp.float32), jnp.ones(b.shape, jnp.float32)))
        plan = u[:, None] * kernel(stages - 1) * v[None, :]
        log_plan = jnp.log(plan + jnp.float32(1e-30))
        log_plan = jnp.where(jnp.isfinite(log_plan) & feasible, log_plan,
                             jnp.float32(-1e30))
        return log_plan, plan

    _SINKHORN_CACHE[key] = sink_run
    return sink_run


# ---------------------------------------------------------------------------
# resident-plane row scatter (the serving tier's device-side delta)
# ---------------------------------------------------------------------------

_SCATTER_CACHE: dict = {}


def resident_row_scatter(mesh: Mesh | None, sharding=None):
    """Jitted `pack.at[rows].set(vals)` for the serving tier's resident
    used-state planes (serving/resident.py): the device-side twin of the
    r13 per-shard delta requantization. Rows/vals are tiny (the cache's
    dirty set — O(assumed pods) per cycle), so under a mesh they ride
    replicated while the (N, 2R+1) pack stays sharded over the nodes
    axis: `out_shardings` pins the result's sharding so the resident
    array never silently de-shards across refreshes (a gathered pack
    would re-pay the full-upload cost the scatter exists to avoid). On
    a single device (mesh=None) it is a plain jitted scatter.

    Cached per (mesh, sharding) like the solver bodies; jax versions
    without jit out_shardings fall back to propagation (correct, at
    worst one re-shard on the next dispatch)."""
    key = (mesh, sharding)
    fn = _SCATTER_CACHE.get(key)
    if fn is not None:
        return fn

    def body(pack, rows, vals):
        return pack.at[rows].set(vals)

    if mesh is not None and sharding is not None:
        try:
            fn = jax.jit(body, out_shardings=sharding)
        except TypeError:  # pragma: no cover - older jax kwarg names
            fn = jax.jit(body)
    else:
        fn = jax.jit(body)
    _SCATTER_CACHE[key] = fn
    return fn


# ---------------------------------------------------------------------------
# phase 2b: multi-slice solver (2-D slice × nodes mesh — config #5)
# ---------------------------------------------------------------------------

def sharded_greedy_assign_multislice(mesh: Mesh, req_q, req_nz_q, free_q,
                                     free_pods, used_nz_q, alloc_q, mask,
                                     static_scores, fit_col_w, bal_col_mask,
                                     shape_u, shape_s, w_fit, w_bal,
                                     strategy: str, shortlist_k: int = 0,
                                     rows=None, exc=None,
                                     row_req_q=None, row_req_nz_q=None,
                                     wave_w: int = 0,
                                     pallas: bool = False,
                                     block_w: int = 0):
    """Sequential-equivalent greedy over a (slice × nodes) mesh: the same
    solver body as `sharded_greedy_assign`, with the node dimension sharded
    over BOTH axes and the per-step argmax reduced hierarchically —
    slice-local `pmax` over ICI, then ONE scalar per slice across DCN, so
    cross-slice traffic is O(1) per pod regardless of node count (the 50k
    config #5 enabler). Tie-break matches the single-device solver.
    wave_w as in sharded_greedy_assign (the wave reductions reduce
    hierarchically through the same axis order)."""
    s_shards = mesh.shape[SLICE_AXIS]
    n_shards = mesh.shape[NODES_AXIS]
    n_total = free_q.shape[0]
    shards = s_shards * n_shards
    assert n_total % shards == 0, (n_total, shards)
    local_n = n_total // shards
    k = min(shortlist_k, local_n - 1) if shortlist_k else 0
    run = _solver_fn(mesh, strategy, local_n,
                     axes=(SLICE_AXIS, NODES_AXIS), shortlist_k=max(k, 0),
                     wave_w=0 if k else max(0, wave_w),
                     pallas=bool(pallas and not k and wave_w > 1),
                     block_w=_block_w_for(block_w, k, local_n))
    p = req_q.shape[0]
    if rows is None:
        rows = jnp.arange(p, dtype=jnp.int32)
    if exc is None:
        exc = jnp.full((p,), -1, dtype=jnp.int32)
    if row_req_q is None:
        row_req_q = req_q
    if row_req_nz_q is None:
        row_req_nz_q = req_nz_q
    return run(req_q, req_nz_q, jnp.asarray(rows), jnp.asarray(exc),
               jnp.asarray(row_req_q), jnp.asarray(row_req_nz_q),
               free_q, free_pods, used_nz_q, alloc_q,
               mask, static_scores, fit_col_w, bal_col_mask,
               jnp.asarray(shape_u), jnp.asarray(shape_s),
               jnp.float32(w_fit), jnp.float32(w_bal))
