"""Device-mesh construction for the scheduling tensors.

SURVEY §2.8/§5.7: the reference scales the Filter/Score fan-out with 16
goroutines over the node list (framework/parallelize) and samples nodes
(`percentageOfNodesToScore`) when clusters get big. The TPU design instead
shards the `(P pods × N nodes)` problem matrix over a `jax.sharding.Mesh`:

- **nodes axis** across chips within a slice (ICI; the TP-like axis) — masks,
  scores, and the solver's per-step argmax reduce across it with
  `pmax`/`pmin` collectives;
- **pods axis** across replicas (the DP-like axis) for the embarrassingly
  parallel mask/score phase;
- multi-slice DCN would add an outer axis to the same specs (the 50k-node
  config #5 path); the code below is mesh-size-agnostic — 1 chip is just a
  (1,)-shaped mesh (SURVEY §7 hard-part #6).
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh

NODES_AXIS = "nodes"
PODS_AXIS = "pods"
SLICE_AXIS = "slice"


def build_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the node axis (the solver's axis)."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (NODES_AXIS,))


def build_multislice_mesh(n_slices: int,
                          chips_per_slice: int | None = None) -> Mesh:
    """(slice × nodes) mesh — BASELINE config #5's 50k-node shape.

    The outer `slice` axis maps to DCN (cross-slice traffic), the inner
    `nodes` axis to ICI within a slice; the cluster's node dimension is
    sharded over BOTH (flattened slice-major), so collectives reduce
    hierarchically: slice-local first (ICI), one scalar per slice across
    DCN second. Under the real multi-slice runtime `jax.devices()` orders
    devices slice-major so rows land on physical slices; on the virtual
    CPU mesh the grouping is positional (what the dryrun proves)."""
    devs = jax.devices()
    if chips_per_slice is None:
        if len(devs) % n_slices:
            raise ValueError(
                f"{len(devs)} devices don't divide into {n_slices} slices")
        chips_per_slice = len(devs) // n_slices
    total = n_slices * chips_per_slice
    if total > len(devs):
        raise ValueError(f"requested {total} devices, have {len(devs)}")
    # The backend pads the node axis to multiples of NODE_PAD (256); a
    # shard count that doesn't divide it fails deep inside XLA sharding —
    # surface it here instead.
    from kubernetes_tpu.ops.tensorize import NODE_PAD
    if NODE_PAD % total:
        raise ValueError(
            f"{n_slices}x{chips_per_slice}={total} shards must divide "
            f"NODE_PAD={NODE_PAD} (use a power-of-two shard count)")
    arr = np.array(devs[:total]).reshape(n_slices, chips_per_slice)
    return Mesh(arr, (SLICE_AXIS, NODES_AXIS))


def build_mesh_2d(n_devices: int | None = None,
                  pods_parallelism: int | None = None) -> Mesh:
    """(pods × nodes) mesh for the mask/score phase. Factorization favors the
    nodes axis (N ≫ P in every BASELINE config)."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    if pods_parallelism is None:
        pods_parallelism = 1
        for f in range(int(math.isqrt(n)), 0, -1):
            if n % f == 0:
                pods_parallelism = f
                break
    assert n % pods_parallelism == 0
    arr = np.array(devs[:n]).reshape(pods_parallelism, n // pods_parallelism)
    return Mesh(arr, (PODS_AXIS, NODES_AXIS))


def pad_axis(x: np.ndarray, multiple: int, axis: int,
             fill=0) -> np.ndarray:
    """Pad one axis up to a multiple so it divides the mesh axis evenly."""
    size = x.shape[axis]
    target = math.ceil(size / multiple) * multiple
    if target == size:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, target - size)
    return np.pad(x, widths, constant_values=fill)
